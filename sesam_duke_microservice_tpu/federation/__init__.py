"""Sharded serving federation (ISSUE 14, ROADMAP item 2).

Everything below this package is ONE serving group: a corpus that must
fit one mesh's HBM, one ingest path serialized through one workload
lock, one link feed.  This package puts a **digest-range partition
router** above N independent groups:

  * ``ranges.py`` — the partition map: the 64-bit routing keyspace
    (``route_key`` over the store record id) split into fixed digest
    ranges, each owned by one group; versioned, epoch-stamped and
    atomically persisted, so a stale router can never write to a
    range's old owner.
  * ``router.py`` — the scatter-gather router: ingest batches partition
    by owner group and fan out with per-group timeouts and bounded
    full-jitter retries; link feeds merge across groups under a
    composite per-range cursor (the opaque federated ``?since=`` token);
    a dead group degrades only ITS ranges (503 + Retry-After) while the
    rest keep serving.
  * ``migrate.py`` — live range rebalancing as a crash-consistent state
    machine (freeze → snapshot → journal-slice replay → cutover →
    drain), built from the primitives PRs 8/10 shipped: checksummed
    state shipping, idempotent ``assert_links``, epoch fencing,
    watermarked journal replay.  Proven by a kill-at-every-site chaos
    differential (tests/test_federation_chaos.py).

``Federation`` (here) assembles the pieces: it builds the N groups from
one ServiceConfig (per-group data folders under ``<root>/federation/
g<i>``), loads-or-creates the partition map, resumes any interrupted
migration, and hands the router to the HTTP frontend
(``service/federation_plane.py``).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

from ..core.config import ServiceConfig
from ..engine.workload import Workload, build_workload
from .migrate import MIGRATION_STATE_FILE, RangeMigrator
from .ranges import PartitionMap, route_key  # noqa: F401  (re-export)
from .router import FederationRouter, LocalGroup

logger = logging.getLogger("federation")

__all__ = [
    "Federation",
    "FederationRouter",
    "LocalGroup",
    "PartitionMap",
    "RangeMigrator",
    "route_key",
]

DEFAULT_RANGES_PER_GROUP = 4


class Federation:
    """N serving groups + partition map + router + migrator, one bundle.

    Each group is a full serving stack (every configured workload built
    via ``build_workload`` over the group's OWN data folder — its own
    record stores, link journals, corpus snapshots), so group state is
    as isolated on disk as it would be across machines; ``LocalGroup``
    is the in-process stand-in for the group's leader endpoint, and the
    router only ever talks through that seam.  A real multi-host
    deployment slots an RPC client into the same seam — the map,
    cursor, fencing and migration semantics are transport-independent.
    """

    def __init__(self, config: ServiceConfig, *, n_groups: int,
                 data_folder: Optional[str] = None,
                 ranges_per_group: int = DEFAULT_RANGES_PER_GROUP,
                 backend: str = "host"):
        if n_groups < 1:
            raise ValueError("a federation needs at least one group")
        self.config = config
        self.backend = backend
        root = data_folder or config.data_folder
        self.data_folder = os.path.join(root, "federation")
        os.makedirs(self.data_folder, exist_ok=True)
        self.map_path = os.path.join(self.data_folder, "partition_map.json")
        self.map = PartitionMap.load_or_create(
            self.map_path, n_groups=n_groups,
            n_ranges=max(1, ranges_per_group) * n_groups)
        if self.map.n_groups != n_groups:
            raise ValueError(
                f"persisted partition map names {self.map.n_groups} "
                f"group(s), but the federation was started with "
                f"{n_groups} — group topology changes go through range "
                "migration, not a restart flag")
        self.groups: List[LocalGroup] = [
            LocalGroup(idx, self._build_group(idx), epoch=self.map.epoch)
            for idx in range(n_groups)
        ]
        self.router = FederationRouter(lambda: self.map, self.groups)
        self.migrator = RangeMigrator(self)
        # one admin migration at a time; the flag flips under the lock,
        # the migration body runs WITHOUT it (it takes workload locks)
        self._admin_lock = threading.Lock()
        self._migrating: Optional[str] = None  # guarded by: self._admin_lock [writes]
        # a migration interrupted by a crash resumes before serving —
        # the frozen range stays frozen (writes 429) until it completes,
        # so resume-at-start mirrors journal recovery's stance: finish
        # the redo before traffic
        if os.path.exists(os.path.join(self.data_folder,
                                       MIGRATION_STATE_FILE)):
            logger.warning("resuming interrupted range migration")
            self.migrator.resume()

    # -- group assembly -------------------------------------------------------

    def group_folder(self, idx: int) -> str:
        return os.path.join(self.data_folder, f"g{idx}")

    def _build_group(self, idx: int) -> Dict[Tuple[str, str], Workload]:
        """Every configured workload, built over group ``idx``'s own
        data folder (journal recovery and store replay run inside
        ``build_workload`` exactly as for a standalone service — scoped
        to the group folder, so one group's replay flips only its own
        readiness)."""
        import dataclasses

        out: Dict[Tuple[str, str], Workload] = {}
        for kind, registry in (("deduplication", self.config.deduplications),
                               ("recordlinkage",
                                self.config.record_linkages)):
            for name, wc in registry.items():
                folder = os.path.join(self.group_folder(idx), kind, name)
                os.makedirs(folder, exist_ok=True)
                gwc = dataclasses.replace(wc, data_folder=folder)
                out[(kind, name)] = build_workload(
                    gwc, self.config, backend=self.backend, persistent=True)
        return out

    def group_folders(self) -> List[str]:
        """Every per-workload data folder across groups — the readiness
        probe's recovery scopes."""
        out = []
        for idx in range(len(self.groups)):
            for (kind, name) in self.groups[idx].workloads:
                out.append(os.path.join(self.group_folder(idx), kind, name))
        return out

    # -- admin: live rebalancing ----------------------------------------------

    def migrate_range(self, range_id: str, target_group: int) -> dict:
        """Move one digest range to ``target_group`` live (the writes to
        that range 429 during the freeze window; reads and every other
        range keep serving).  Serialized: one migration at a time."""
        with self._admin_lock:
            if self._migrating is not None:
                raise RuntimeError(
                    f"migration of range {self._migrating} already in "
                    "progress")
            self._migrating = range_id
        try:
            return self.migrator.migrate(range_id, target_group)
        finally:
            with self._admin_lock:
                self._migrating = None

    def migration_status(self) -> dict:
        return self.migrator.status()

    def close(self) -> None:
        for group in self.groups:
            group.close()
