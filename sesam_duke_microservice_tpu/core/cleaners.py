"""Cleaner registry — value normalization applied at ingest.

The reference config references Duke 1.2 cleaners by Java class name
(e.g. ``no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner`` at
testdukeconfig.xml:66, ``no.priv.garshol.duke.examples.CountryNameCleaner`` at
testdukeconfig.xml:50).  This module provides behavior-compatible Python
implementations registered under both the full Java class names (so existing
reference configs load unchanged) and short snake-case aliases.

Cleaners are host-side: they run once per value at ingest, before
tokenization, so they are not on the device hot path.  A cleaner returns the
cleaned string, or ``None``/``""`` to drop the value entirely (Duke
convention).
"""

from __future__ import annotations

import re
import unicodedata
from typing import Callable, Dict, Optional

Cleaner = Callable[[str], Optional[str]]

_REGISTRY: Dict[str, Cleaner] = {}


def register_cleaner(*names: str):
    def deco(fn: Cleaner) -> Cleaner:
        for name in names:
            _REGISTRY[name] = fn
        return fn

    return deco


def get_cleaner(name: str) -> Cleaner:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"Unknown cleaner '{name}'. Known cleaners: {sorted(_REGISTRY)}"
        ) from None


def has_cleaner(name: str) -> bool:
    return name in _REGISTRY


def available_cleaners():
    return sorted(_REGISTRY)


_WS_RE = re.compile(r"\s+")
_PAREN_RE = re.compile(r"\s*\([^)]*\)")


def _strip_accents(value: str) -> str:
    decomposed = unicodedata.normalize("NFKD", value)
    return "".join(ch for ch in decomposed if not unicodedata.combining(ch))


@register_cleaner(
    "no.priv.garshol.duke.cleaners.LowerCaseNormalizeCleaner",
    "LowerCaseNormalizeCleaner",
    "lowercase",
)
def lower_case_normalize(value: str) -> str:
    """Lowercase, strip accents, collapse whitespace, trim."""
    value = _strip_accents(value).lower()
    value = _WS_RE.sub(" ", value).strip()
    return value


@register_cleaner("no.priv.garshol.duke.cleaners.TrimCleaner", "TrimCleaner", "trim")
def trim(value: str) -> str:
    return value.strip()


@register_cleaner(
    "no.priv.garshol.duke.cleaners.DigitsOnlyCleaner", "DigitsOnlyCleaner", "digits"
)
def digits_only(value: str) -> str:
    return "".join(ch for ch in value if ch.isdigit())


@register_cleaner(
    "no.priv.garshol.duke.cleaners.PhoneNumberCleaner",
    "PhoneNumberCleaner",
    "phone",
)
def phone_number(value: str) -> str:
    """Keep digits; normalize an international prefix ('+'/'00') away."""
    digits = "".join(ch for ch in value if ch.isdigit())
    if value.strip().startswith("+"):
        return digits
    if digits.startswith("00"):
        return digits[2:]
    return digits


@register_cleaner(
    "no.priv.garshol.duke.cleaners.FamilyCommaGivenCleaner",
    "FamilyCommaGivenCleaner",
    "family-comma-given",
)
def family_comma_given(value: str) -> str:
    """'Family, Given' -> 'given family', then lowercase-normalize."""
    if "," in value:
        family, _, given = value.partition(",")
        value = f"{given.strip()} {family.strip()}"
    return lower_case_normalize(value)


@register_cleaner(
    "no.priv.garshol.duke.cleaners.NorwegianCompanyNameCleaner",
    "NorwegianCompanyNameCleaner",
    "norwegian-company",
)
def norwegian_company_name(value: str) -> str:
    """Lowercase-normalize and drop Norwegian company-form suffixes (AS, ASA...)."""
    value = lower_case_normalize(value)
    tokens = [t for t in value.split(" ") if t not in {"as", "asa", "ans", "ba", "da", "sa"}]
    return " ".join(tokens)


@register_cleaner(
    "no.priv.garshol.duke.cleaners.NorwegianAddressCleaner",
    "NorwegianAddressCleaner",
    "norwegian-address",
)
def norwegian_address(value: str) -> str:
    """Lowercase-normalize and normalize common street-type abbreviations."""
    value = lower_case_normalize(value)
    replacements = {
        "gt.": "gate",
        "gt": "gate",
        "vn.": "veien",
        "vn": "veien",
        "v.": "veien",
        "pb.": "postboks",
        "pb": "postboks",
    }
    tokens = [replacements.get(t, t) for t in value.split(" ")]
    return " ".join(tokens)


# Alias tables for the two demo-config example cleaners.  The reference relies
# on Duke's example classes (testdukeconfig.xml:50,55); these reproduce their
# intent (normalize country/capital names so the DBpedia and Mondial datasets
# agree) without claiming byte-level parity with the Java examples.
_COUNTRY_ALIASES = {
    "usa": "united states",
    "united states of america": "united states",
    "us": "united states",
    "uk": "united kingdom",
    "great britain": "united kingdom",
    "holland": "netherlands",
    "the netherlands": "netherlands",
    "russian federation": "russia",
    "republic of korea": "south korea",
    "korea, south": "south korea",
    "korea, north": "north korea",
    "democratic people's republic of korea": "north korea",
    "cote d'ivoire": "ivory coast",
    "burma": "myanmar",
}


@register_cleaner(
    "no.priv.garshol.duke.examples.CountryNameCleaner",
    "CountryNameCleaner",
    "country",
)
def country_name(value: str) -> str:
    value = lower_case_normalize(value)
    value = _PAREN_RE.sub("", value).strip()
    for prefix in ("republic of ", "kingdom of ", "state of "):
        if value.startswith(prefix) and value[len(prefix):] not in ("korea",):
            value = value[len(prefix):]
            break
    return _COUNTRY_ALIASES.get(value, value)


@register_cleaner(
    "no.priv.garshol.duke.examples.CapitalCleaner",
    "CapitalCleaner",
    "capital",
)
def capital(value: str) -> str:
    """City names: drop parenthesized qualifiers and 'City' suffixes."""
    value = lower_case_normalize(value)
    value = _PAREN_RE.sub("", value).strip()
    if value.endswith(" city"):
        value = value[: -len(" city")]
    return value


class RegexpCleaner:
    """Duke's RegexpCleaner: extract a regexp group from the value.

    Instantiated from config ``<object>`` definitions with params ``regexp``
    and optional ``group-no`` (default 1).
    """

    def __init__(self, regexp: str, group_no: int = 1):
        self.pattern = re.compile(regexp)
        self.group_no = int(group_no)

    def __call__(self, value: str) -> Optional[str]:
        m = self.pattern.search(value)
        if not m:
            return None
        try:
            return m.group(self.group_no)
        except IndexError:
            return None


class MappingCleaner:
    """Dictionary-based replacement cleaner (Duke's MappingFileCleaner shape)."""

    def __init__(self, mapping: Dict[str, str], sub_cleaner: Optional[Cleaner] = None):
        self.mapping = mapping
        self.sub_cleaner = sub_cleaner

    def __call__(self, value: str) -> Optional[str]:
        if self.sub_cleaner is not None:
            value = self.sub_cleaner(value) or ""
        return self.mapping.get(value, value)


class ChainedCleaner:
    """Apply cleaners in sequence, dropping the value if any returns None."""

    def __init__(self, *cleaners: Cleaner):
        self.cleaners = cleaners

    def __call__(self, value: str) -> Optional[str]:
        for cleaner in self.cleaners:
            if value is None:
                return None
            value = cleaner(value)
        return value
