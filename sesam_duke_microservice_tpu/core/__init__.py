from .records import Record, Property, Lookup, SchemaError
from .bayes import compute_bayes, combine_probabilities, probability_logit

__all__ = [
    "Record",
    "Property",
    "Lookup",
    "SchemaError",
    "compute_bayes",
    "combine_probabilities",
    "probability_logit",
]
