"""Core record / property model.

Re-expresses the slice of the Duke 1.2 API that the reference microservice
drives (``Record``/``ModifiableRecord``, ``Property``/``PropertyImpl``,
``Property.Lookup`` — imported at ``/root/reference/src/main/java/io/sesam/
dukemicroservice/App.java:58-71``) as plain Python types.  These are host-side
bookkeeping objects only; the hot matching path operates on padded token
tensors (see ``ops.tokenize`` / ``engine.device_matcher``), never on these.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence

# Hidden property names the service injects into every schema
# (reference: IncrementalLuceneDatabase.java:449-452).
GROUP_NO_PROPERTY_NAME = "dukeGroupNo"
DATASET_ID_PROPERTY_NAME = "dukeDatasetId"
ORIGINAL_ENTITY_ID_PROPERTY_NAME = "dukeOriginalEntityId"
DELETED_PROPERTY_NAME = "dukeDeleted"
ID_PROPERTY_NAME = "ID"


class SchemaError(Exception):
    """Raised for invalid schema/config combinations (Duke's DukeConfigException)."""


class Lookup(enum.Enum):
    """Per-property candidate-lookup behaviour (Duke's ``Property.Lookup``).

    The blocking database uses this to decide which properties participate in
    candidate retrieval and whether their match is required
    (reference: IncrementalLuceneDatabase.java:481-487).
    """

    DEFAULT = "default"
    REQUIRED = "required"
    TRUE = "true"
    FALSE = "false"
    IGNORE = "ignore"


class Property:
    """A schema property: comparator + [low, high] probability range.

    Mirrors Duke's ``PropertyImpl`` semantics as driven by the reference
    (App.java:309-325): id properties carry record identity and are never
    compared; ignored properties are stored but not compared; the remaining
    properties contribute evidence via ``compare_probability``.
    """

    def __init__(
        self,
        name: str,
        comparator=None,
        low: float = 0.0,
        high: float = 0.0,
        *,
        id_property: bool = False,
        ignore: bool = False,
        lookup: Lookup = Lookup.DEFAULT,
    ):
        self.name = name
        self.comparator = comparator
        self.low = float(low)
        self.high = float(high)
        self.id_property = id_property
        self.ignore = ignore
        self.lookup = lookup

    def compare_probability(self, v1: str, v2: str) -> float:
        """Map comparator similarity to a match probability.

        Duke's ``PropertyImpl.compare``: properties without a comparator are
        neutral (0.5); similarity >= 0.5 maps quadratically into
        ``(0.5, high]``, anything below maps to ``low``.
        """
        if self.comparator is None:
            return 0.5
        sim = self.comparator.compare(v1, v2)
        if sim >= 0.5:
            return ((self.high - 0.5) * (sim * sim)) + 0.5
        return self.low

    def __repr__(self) -> str:
        flags = []
        if self.id_property:
            flags.append("id")
        if self.ignore:
            flags.append("ignore")
        return (
            f"Property({self.name!r}, low={self.low}, high={self.high}"
            + (", " + "|".join(flags) if flags else "")
            + ")"
        )


class Record:
    """A record: property name -> list of string values.

    Equivalent of Duke's ``ModifiableRecord`` as built by the reference's
    ingest datasource (IncrementalDataSource.java:62-100).  Values are always
    strings; empty strings are never stored (Duke's RecordBuilder drops them).
    """

    __slots__ = ("_values", "_digest_cache", "_id_cache")

    def __init__(self, values: Optional[Dict[str, List[str]]] = None):
        self._values: Dict[str, List[str]] = {}
        # memoized content digest (store.records.record_digest): the
        # persistent ingest path digests every record twice (store row +
        # index fold); mutation invalidates
        self._digest_cache: Optional[bytes] = None
        # memoized record_id: the ingest bookkeeping path (corpus append,
        # id_to_row, digests, listeners) reads it several times per record
        self._id_cache: Optional[str] = None
        if values:
            for name, vals in values.items():
                for v in vals:
                    self.add_value(name, v)

    def add_value(self, prop: str, value: Optional[str]) -> None:
        if value is None or value == "":
            return
        self._values.setdefault(prop, []).append(str(value))
        self._digest_cache = None
        self._id_cache = None

    def set_values(self, prop: str, values: List[str]) -> None:
        """Replace one property's value list (invalidates the memos —
        callers must never poke ``_values`` directly).  Empty values are
        dropped like ``add_value`` does, and a fully-empty list removes
        the key: a stored empty list would serialize differently from its
        own store round-trip (add_value never creates one) and trip the
        store/index divergence latch."""
        filtered = [str(v) for v in values if v]
        if filtered:
            self._values[prop] = filtered
        else:
            self._values.pop(prop, None)
        self._digest_cache = None
        self._id_cache = None

    def properties(self) -> Sequence[str]:
        return list(self._values.keys())

    def get_values(self, prop: str) -> List[str]:
        # a COPY: handing out the live list would let callers mutate the
        # record behind add_value's back (the digest memo must see every
        # mutation, and Duke records are value objects)
        return list(self._values.get(prop, ()))

    def get_value(self, prop: str) -> Optional[str]:
        vals = self._values.get(prop)
        return vals[0] if vals else None

    @property
    def record_id(self) -> Optional[str]:
        rid = self._id_cache
        if rid is None:
            rid = self._id_cache = self.get_value(ID_PROPERTY_NAME)
        return rid

    def is_deleted(self) -> bool:
        return self.get_value(DELETED_PROPERTY_NAME) == "true"

    def to_dict(self) -> Dict[str, List[str]]:
        return {k: list(v) for k, v in self._values.items()}

    def __eq__(self, other) -> bool:
        return isinstance(other, Record) and self._values == other._values

    def __repr__(self) -> str:
        return f"Record({self._values!r})"
