"""Naive-Bayes probability combination (Duke's ``Utils.computeBayes``).

The matching engine combines per-property match probabilities with the
classic naive-Bayes odds product, starting from a 0.5 prior (reference hot
loop: SURVEY.md section 3.2; driven from App.java:1005 / App.java:1159 into the
Duke jar).  The equivalent log-odds form used on device is::

    combined = sigmoid(sum_i logit(p_i))

which is exactly the repeated ``compute_bayes`` fold — on TPU the combine is
therefore a masked sum over a logit tensor (see ops.bayes).
"""

from __future__ import annotations

import math
from typing import Iterable

# Probabilities are clamped away from {0, 1} so a single certain property
# cannot produce inf logits; 1e-10 keeps us well inside float32 on device.
_EPS = 1e-10


def compute_bayes(p1: float, p2: float) -> float:
    """Combine two probabilities: ``p1*p2 / (p1*p2 + (1-p1)*(1-p2))``."""
    num = p1 * p2
    den = num + (1.0 - p1) * (1.0 - p2)
    if den == 0.0:
        return 0.5
    return num / den


def probability_logit(p: float) -> float:
    """log-odds of p, clamped to avoid infinities."""
    p = min(max(p, _EPS), 1.0 - _EPS)
    return math.log(p / (1.0 - p))


def combine_probabilities(probabilities: Iterable[float]) -> float:
    """Fold probabilities with naive Bayes from a 0.5 prior.

    Implemented in log-odds space (mathematically identical to the pairwise
    ``compute_bayes`` fold, and the formulation the device kernels use).
    """
    total = 0.0
    for p in probabilities:
        total += probability_logit(p)
    return 1.0 / (1.0 + math.exp(-total))
