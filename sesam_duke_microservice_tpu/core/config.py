"""Two-level XML config parser.

Reproduces the reference's config system (App.java:227-647): an outer
``<DukeMicroService dataFolder=...>`` element containing ``<Deduplication
name=...>`` and ``<RecordLinkage name=... link-mode=... link-database-type=...>``
workloads, each wrapping a ``<duke>`` element in Duke 1.2's own XML schema
(``<object>`` bean definitions, ``<schema>`` with threshold + properties,
``<data-source>`` with columns/cleaners, ``<group>`` blocks for linkage —
see testdukeconfig.xml).  The service injects hidden properties into every
schema (ID, dukeDatasetId, dukeOriginalEntityId, dukeDeleted, and dukeGroupNo
for linkage — App.java:309-325 / 426-446) and applies the same validation
rules (no user id property App.java:303-307; no '_id'/'id' columns
App.java:378-384; datasource class + dataset-id checks App.java:360-394).

Divergences from the reference (documented, deliberate):
  * a missing ``link-mode`` attribute raises ``ConfigError`` with a clear
    message (the reference NPEs, App.java:411);
  * ``link-database-type="sqlite"`` is accepted as an alias for ``"h2"``
    (our durable backend is SQLite rather than embedded H2).
"""

from __future__ import annotations

import os
import re
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from . import cleaners as cleaners_mod
from . import comparators as comparators_mod
from .records import (
    DATASET_ID_PROPERTY_NAME,
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    ORIGINAL_ENTITY_ID_PROPERTY_NAME,
    Lookup,
    Property,
)

DEDUP_DATASOURCE_CLASS = "io.sesam.dukemicroservice.IncrementalDeduplicationDataSource"
LINKAGE_DATASOURCE_CLASS = "io.sesam.dukemicroservice.IncrementalRecordLinkageDataSource"


class ConfigError(Exception):
    pass


@dataclass
class Column:
    name: str
    property: str
    cleaner: Optional[Callable[[str], Optional[str]]] = None
    cleaner_name: Optional[str] = None


@dataclass
class DataSourceConfig:
    dataset_id: str
    columns: List[Column]
    group_no: Optional[int] = None


@dataclass
class DukeSchema:
    """Parsed inner <duke> element: schema + datasources."""

    threshold: float
    maybe_threshold: Optional[float]
    properties: List[Property]
    data_sources: List[DataSourceConfig]          # dedup: flat list
    groups: List[List[DataSourceConfig]] = field(default_factory=list)  # linkage

    def property_by_name(self, name: str) -> Optional[Property]:
        for p in self.properties:
            if p.name == name:
                return p
        return None

    def comparison_properties(self) -> List[Property]:
        return [p for p in self.properties if not p.id_property and not p.ignore]

    def lookup_properties(self) -> List[Property]:
        """Properties used for candidate retrieval.

        Default: every comparison property; explicit lookup="false"/"ignore"
        excludes a property (cf. IncrementalLuceneDatabase.java:481-487).
        """
        return [
            p
            for p in self.comparison_properties()
            if p.lookup not in (Lookup.FALSE, Lookup.IGNORE)
        ]


@dataclass
class MatchTunables:
    """Env-driven candidate-search tunables (App.java:550-564 defaults)."""

    min_relevance: float = 0.9
    fuzzy_search: bool = False
    max_search_hits: int = 10

    @classmethod
    def from_env(cls, env=os.environ) -> "MatchTunables":  # dukecheck: ignore[DK301] injectable env= seam (tests pass dicts); reference parity requires raw strings
        t = cls()
        if env.get("MIN_RELEVANCE"):
            t.min_relevance = float(env["MIN_RELEVANCE"])
        if env.get("FUZZY_SEARCH"):
            t.fuzzy_search = env["FUZZY_SEARCH"].strip().lower() == "true"
        if env.get("MAX_SEARCH_HITS"):
            t.max_search_hits = int(env["MAX_SEARCH_HITS"])
        return t


@dataclass
class WorkloadConfig:
    name: str
    kind: str                       # "deduplication" | "recordlinkage"
    duke: DukeSchema
    link_database_type: str         # "h2" | "in-memory"
    # linkage only: "one-to-one" (enforced per workload) or "many-to-many"
    # (accepted extension value — every above-threshold pair links, the
    # reference's de-facto behavior since its flag is vestigial, quirk Q5)
    link_mode: Optional[str] = None
    data_folder: Optional[str] = None

    @property
    def is_record_linkage(self) -> bool:
        return self.kind == "recordlinkage"

    @property
    def enforce_one_to_one(self) -> bool:
        """Whether THIS workload's XML asks for one-to-one enforcement.

        The reference parses link-mode="one-to-one" per <RecordLinkage>
        element (App.java:113-120) but never reads the flag (quirk Q5);
        here the attribute is the thing that controls behavior, so two
        linkage workloads in one config can run different modes.  The
        ONE_TO_ONE env flag overrides globally (see ServiceConfig)."""
        return self.is_record_linkage and self.link_mode == "one-to-one"


@dataclass
class ServiceConfig:
    config_string: str
    data_folder: str
    deduplications: Dict[str, WorkloadConfig]
    record_linkages: Dict[str, WorkloadConfig]
    threads: int = 1
    profile: bool = False
    tunables: MatchTunables = field(default_factory=MatchTunables)
    # Global one-to-one override: None (default) defers to each linkage
    # workload's link-mode attribute (WorkloadConfig.enforce_one_to_one);
    # ONE_TO_ONE=1 forces enforcement on for every linkage workload,
    # ONE_TO_ONE=0 forces it off (restoring the reference's vestigial-flag
    # behavior, quirk Q5).
    one_to_one: Optional[bool] = None


def _parse_number(text: str, what: str, label: str) -> float:
    try:
        return float(text)
    except (TypeError, ValueError):
        raise ConfigError(f"Invalid {what} value '{text}' in the {label}") from None


def _instantiate_object(class_name: str, params: Dict[str, str]):
    """Instantiate an <object> bean: comparator or cleaner."""
    if comparators_mod.has_comparator(class_name):
        obj = comparators_mod.make_comparator(class_name)
        for pname, pvalue in params.items():
            try:
                obj.set_param(pname, pvalue)
            except (KeyError, ValueError) as e:
                raise ConfigError(
                    f"Invalid param '{pname}'='{pvalue}' for <object> "
                    f"class '{class_name}': {e}"
                ) from None
        return obj
    if class_name.endswith("RegexpCleaner"):
        return cleaners_mod.RegexpCleaner(
            params.get("regexp", ".*"), int(params.get("group-no", 1) or 1)
        )
    if cleaners_mod.has_cleaner(class_name):
        return cleaners_mod.get_cleaner(class_name)
    raise ConfigError(f"Unknown <object> class '{class_name}'")


def _resolve_comparator(name: str, objects: Dict[str, object]):
    """Resolve a <comparator> reference to an instance.

    Duke's ConfigLoader semantics: a reference matching a named <object> uses
    that (parameterized) instance; anything else instantiates a fresh
    comparator with default params.  Note the bundled demo config defines an
    'AreaComparator' object but references the class name, so its min-ratio
    is never applied — faithfully reproduced here.
    """
    if name in objects:
        obj = objects[name]
        if not isinstance(obj, comparators_mod.Comparator):
            raise ConfigError(f"<object> '{name}' referenced as comparator is not one")
        return obj
    if comparators_mod.has_comparator(name):
        return comparators_mod.make_comparator(name)
    raise ConfigError(f"Unknown comparator '{name}'")


def _resolve_cleaner(name: str, objects: Dict[str, object]):
    if name in objects:
        obj = objects[name]
        if not callable(obj):
            raise ConfigError(f"<object> '{name}' referenced as cleaner is not callable")
        return obj
    if cleaners_mod.has_cleaner(name):
        return cleaners_mod.get_cleaner(name)
    raise ConfigError(f"Unknown cleaner '{name}'")


def _parse_params(element: ET.Element) -> Dict[str, str]:
    params = {}
    for p in element.findall("param"):
        params[p.get("name", "")] = p.get("value", "")
    return params


def _parse_data_source(ds_el: ET.Element, objects: Dict[str, object],
                       expected_class: str, workload_label: str) -> DataSourceConfig:
    cls = ds_el.get("class", "")
    if cls != expected_class:
        raise ConfigError(
            f"Got a DataSource of the unsupported type '{cls}' in the {workload_label}! "
            f"(expected '{expected_class}')"
        )
    params = _parse_params(ds_el)
    dataset_id = params.get("dataset-id", "")
    if not dataset_id:
        raise ConfigError(
            f"Got a DataSource with no datasetId property in the {workload_label}!"
        )
    columns = []
    for col_el in ds_el.findall("column"):
        col_name = col_el.get("name", "")
        if col_name.lower() in ("_id", "id"):
            raise ConfigError(
                f"The DataSource '{dataset_id}' in the {workload_label} contained "
                f"an '{col_name}' column!"
            )
        prop = col_el.get("property", "")
        if not prop:
            raise ConfigError(
                f"Column '{col_name}' in DataSource '{dataset_id}' has no property"
            )
        cleaner_name = col_el.get("cleaner")
        cleaner = _resolve_cleaner(cleaner_name, objects) if cleaner_name else None
        columns.append(Column(col_name, prop, cleaner, cleaner_name))
    return DataSourceConfig(dataset_id=dataset_id, columns=columns)


def parse_duke_element(duke_el: ET.Element, *, is_record_linkage: bool,
                       workload_label: str) -> DukeSchema:
    """Parse the inner <duke> element (Duke 1.2 config schema subset)."""
    objects: Dict[str, object] = {}
    for obj_el in duke_el.findall("object"):
        name = obj_el.get("name")
        cls = obj_el.get("class", "")
        if not name:
            raise ConfigError(f"<object> without a name in the {workload_label}")
        objects[name] = _instantiate_object(cls, _parse_params(obj_el))

    schema_el = duke_el.find("schema")
    if schema_el is None:
        raise ConfigError(f"The {workload_label} <duke> element has no <schema>!")

    thr_el = schema_el.find("threshold")
    if thr_el is None or thr_el.text is None:
        raise ConfigError(f"The {workload_label} schema has no <threshold>!")
    threshold = _parse_number(thr_el.text.strip(), "threshold", workload_label)
    maybe_el = schema_el.find("maybe-threshold")
    maybe_threshold = (
        _parse_number(maybe_el.text.strip(), "maybe-threshold", workload_label)
        if maybe_el is not None and maybe_el.text
        else None
    )

    properties: List[Property] = []
    for prop_el in schema_el.findall("property"):
        ptype = prop_el.get("type", "")
        name_el = prop_el.find("name")
        if name_el is None or not (name_el.text or "").strip():
            raise ConfigError(f"A <property> in the {workload_label} has no <name>")
        pname = name_el.text.strip()
        if ptype == "id":
            # mirrors App.java:303-307 — the service owns record identity
            raise ConfigError(
                f"The schema contained an 'id'-property: '{pname}'"
            )
        if ptype == "ignore":
            properties.append(Property(pname, ignore=True))
            continue
        comp_el = prop_el.find("comparator")
        comparator = None
        if comp_el is not None and (comp_el.text or "").strip():
            comparator = _resolve_comparator(comp_el.text.strip(), objects)
        low_el = prop_el.find("low")
        high_el = prop_el.find("high")
        low = (
            _parse_number(low_el.text.strip(), "low", workload_label)
            if low_el is not None and low_el.text else 0.3
        )
        high = (
            _parse_number(high_el.text.strip(), "high", workload_label)
            if high_el is not None and high_el.text else 0.95
        )
        lookup_raw = prop_el.get("lookup", "default")
        try:
            lookup = Lookup(lookup_raw)
        except ValueError:
            raise ConfigError(
                f"Invalid lookup value '{lookup_raw}' on property '{pname}' "
                f"in the {workload_label}"
            ) from None
        properties.append(Property(pname, comparator, low, high, lookup=lookup))

    # Hidden-property injection (App.java:309-325 / 426-446)
    properties.append(Property(ID_PROPERTY_NAME, id_property=True))
    properties.append(Property(DATASET_ID_PROPERTY_NAME, ignore=True))
    properties.append(Property(ORIGINAL_ENTITY_ID_PROPERTY_NAME, ignore=True))
    if is_record_linkage:
        properties.append(Property(GROUP_NO_PROPERTY_NAME, ignore=True))
    properties.append(Property(DELETED_PROPERTY_NAME, ignore=True))

    data_sources: List[DataSourceConfig] = []
    groups: List[List[DataSourceConfig]] = []
    if is_record_linkage:
        group_els = duke_el.findall("group")
        if len(group_els) != 2:
            raise ConfigError(
                f"The {workload_label} must have exactly two <group> elements "
                f"(got {len(group_els)})"
            )
        for group_no, group_el in enumerate(group_els, start=1):
            group_sources = []
            for ds_el in group_el.findall("data-source"):
                ds = _parse_data_source(
                    ds_el, objects, LINKAGE_DATASOURCE_CLASS, workload_label
                )
                ds.group_no = group_no
                group_sources.append(ds)
            if not group_sources:
                raise ConfigError(
                    f"Got zero datasources for group {group_no} in the {workload_label}!"
                )
            groups.append(group_sources)
            data_sources.extend(group_sources)
    else:
        for ds_el in duke_el.findall("data-source"):
            data_sources.append(
                _parse_data_source(ds_el, objects, DEDUP_DATASOURCE_CLASS, workload_label)
            )
        if not data_sources:
            raise ConfigError(f"Got zero datasources in the {workload_label}!")

    return DukeSchema(
        threshold=threshold,
        maybe_threshold=maybe_threshold,
        properties=properties,
        data_sources=data_sources,
        groups=groups,
    )


def _find_duke_child(workload_el: ET.Element, workload_label: str) -> ET.Element:
    duke_el = None
    for child in workload_el:
        if child.tag == "duke":
            duke_el = child
        else:
            raise ConfigError(
                f"Unknown element '{child.tag}' found in the {workload_label}!"
            )
    if duke_el is None:
        raise ConfigError(f"The {workload_label} didn't contain a <duke> element!")
    return duke_el


def _link_database_type(el: ET.Element, name: str) -> str:
    ldt = el.get("link-database-type", "") or "h2"
    if ldt == "sqlite":
        ldt = "h2"
    if ldt not in ("h2", "in-memory"):
        raise ConfigError(f"Got an unknown 'link-database-type' value: '{ldt}'")
    return ldt


def parse_config(config_string: str, env=os.environ) -> ServiceConfig:  # dukecheck: ignore[DK301] injectable env= seam
    """Parse a full service config string (the POST /config payload shape)."""
    try:
        root = ET.fromstring(config_string)
    except ET.ParseError as e:
        raise ConfigError(f"Invalid XML: {e}") from e

    if root.tag == "DukeMicroService":
        service_els = [root]
    else:
        service_els = list(root.iter("DukeMicroService"))
    if len(service_els) == 0:
        raise ConfigError("The configfile didn't contain a 'DukeMicroService' entity!")
    if len(service_els) > 1:
        raise ConfigError("The configfile contain more than one 'DukeMicroService' entity!")
    service_el = service_els[0]

    data_folder = service_el.get("dataFolder") or os.path.join(os.getcwd(), "data")

    threads = 1
    threads_env = env.get("THREADS")
    if threads_env and re.fullmatch(r"\d+", threads_env):
        threads = int(threads_env)
    profile = env.get("PROFILE") == "1"
    oto_env = (env.get("ONE_TO_ONE") or "").strip().lower()
    one_to_one = (True if oto_env in ("1", "true")
                  else False if oto_env in ("0", "false") else None)
    tunables = MatchTunables.from_env(env)

    deduplications: Dict[str, WorkloadConfig] = {}
    record_linkages: Dict[str, WorkloadConfig] = {}
    for child in service_el:
        if child.tag == "Deduplication":
            name = child.get("name")
            if not name:
                raise ConfigError("A <Deduplication> element has no name attribute")
            label = f"deduplication '{name}'"
            duke = parse_duke_element(
                _find_duke_child(child, label), is_record_linkage=False, workload_label=label
            )
            deduplications[name] = WorkloadConfig(
                name=name,
                kind="deduplication",
                duke=duke,
                link_database_type=_link_database_type(child, name),
                data_folder=os.path.join(data_folder, "deduplication", name),
            )
        elif child.tag == "RecordLinkage":
            name = child.get("name")
            if not name:
                raise ConfigError("A <RecordLinkage> element has no name attribute")
            label = f"recordLinkage '{name}'"
            link_mode = child.get("link-mode")
            if link_mode is None:
                raise ConfigError(
                    f"The {label} has no link-mode attribute (must be "
                    f"'one-to-one' or 'many-to-many')"
                )
            if link_mode not in ("one-to-one", "many-to-many"):
                # documented divergence: the reference accepts only
                # "one-to-one" (App.java:113-120); "many-to-many" is the
                # extension value naming its actual (unenforced) behavior
                raise ConfigError(
                    f"Invalid link-mode '{link_mode}' specified for the '{name}' recordlinkage."
                )
            duke = parse_duke_element(
                _find_duke_child(child, label), is_record_linkage=True, workload_label=label
            )
            record_linkages[name] = WorkloadConfig(
                name=name,
                kind="recordlinkage",
                duke=duke,
                link_database_type=_link_database_type(child, name),
                link_mode=link_mode,
                data_folder=os.path.join(data_folder, "recordLinkage", name),
            )
        else:
            raise ConfigError(
                f"Unknown element '{child.tag}' found in the configuration file!"
            )

    return ServiceConfig(
        config_string=config_string,
        data_folder=data_folder,
        deduplications=deduplications,
        record_linkages=record_linkages,
        threads=threads,
        profile=profile,
        tunables=tunables,
        one_to_one=one_to_one,
    )


DEFAULT_CONFIG_RESOURCE = os.path.join(
    os.path.dirname(__file__), "..", "resources", "testdukeconfig.xml"
)


def load_default_config(env=os.environ) -> ServiceConfig:  # dukecheck: ignore[DK301] injectable env= seam
    """Load CONFIG_STRING from the environment, else the bundled demo config
    (mirrors App.java:200-224)."""
    config_string = env.get("CONFIG_STRING")
    if not config_string:
        with open(os.path.abspath(DEFAULT_CONFIG_RESOURCE), "r", encoding="utf-8") as f:
            config_string = f.read()
    return parse_config(config_string, env=env)
