"""Host-side comparator implementations (oracles + conformance path).

The reference delegates per-pair similarity to Duke 1.2 comparator classes
selected by Java class name in the XML schema (testdukeconfig.xml:27,33;
SURVEY.md section 1 L1).  This module provides behavior-compatible Python
implementations, registered under the Duke class names so reference configs
load unchanged, plus short aliases.

These scalar implementations serve three roles:
  1. the conformance/"oracle" reference for the batched device kernels in
     ``ops/`` (each kernel has differential tests against these),
  2. the scoring path of the pure-host engine backend (useful for CPU-only
     runs and golden tests),
  3. documentation of the exact similarity semantics the framework promises.

Every comparator exposes ``compare(v1, v2) -> float`` in [0, 1] and an
``is_tokenized`` flag (Duke's ``Comparator.isTokenized``; the blocking layer
uses it for its fuzzy-search decision, IncrementalLuceneDatabase.java:323-326).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Type


def _native_module():
    """The C++ comparator library (native/), or None.

    Resolved lazily on first compare so importing this module never pays
    the compile; pure-Python bodies below stay authoritative as oracles
    (tests/test_native.py pins native<->Python parity).
    """
    global _NATIVE
    if _NATIVE is _UNRESOLVED:
        try:
            from .. import native

            _NATIVE = native if native.available() else None
        except Exception:  # toolchain/load problems: stay pure-Python
            _NATIVE = None
    return _NATIVE


_UNRESOLVED = object()
_NATIVE = _UNRESOLVED


class Comparator:
    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:  # pragma: no cover - abstract
        raise NotImplementedError

    def set_param(self, name: str, value: str) -> None:
        """Bean-style param injection from config ``<object>``/``<param>``.

        Kebab-case param names map to python attributes
        (``min-ratio`` -> ``min_ratio``), with numeric coercion.
        """
        attr = name.replace("-", "_")
        if not hasattr(self, attr):
            raise KeyError(f"{type(self).__name__} has no parameter '{name}'")
        current = getattr(self, attr)
        if isinstance(current, bool):
            value = value.lower() == "true"
        elif isinstance(current, int):
            value = int(value)
        elif isinstance(current, float):
            value = float(value)
        setattr(self, attr, value)


def levenshtein_distance(s1: str, s2: str, limit: Optional[int] = None) -> int:
    """Plain dynamic-programming edit distance (optionally bounded by limit)."""
    if s1 == s2:
        return 0
    n1, n2 = len(s1), len(s2)
    if n1 == 0:
        return n2
    if n2 == 0:
        return n1
    prev = list(range(n2 + 1))
    for i in range(1, n1 + 1):
        cur = [i] + [0] * n2
        c1 = s1[i - 1]
        best = cur[0]
        for j in range(1, n2 + 1):
            cost = 0 if c1 == s2[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if cur[j] < best:
                best = cur[j]
        if limit is not None and best > limit:
            return limit + 1
        prev = cur
    return prev[n2]


def _utf16_expand(s: str) -> str:
    """Java parity for char-based comparators: Duke measures edit
    distance over java.lang.String CHAR UNITS, so a non-BMP character
    (surrogate pair in Java) counts as TWO positions.  The device path
    stores UTF-16 code units outright (ops.features.CHAR_DTYPE); this
    expansion keeps the host comparators bit-identical to it.  BMP-only
    strings (the overwhelmingly common case) return unchanged."""
    if s.isascii():  # O(1) flag check covers the hot loop's usual case
        return s
    for ch in s:
        if ord(ch) > 0xFFFF:
            return "".join(
                chr(u) for u in
                memoryview(s.encode("utf-16-le", "surrogatepass")).cast("H")
            )
    return s


class Levenshtein(Comparator):
    """Edit-distance similarity, Duke semantics.

    ``sim = 1 - d / min_len`` with two Duke-specific twists: strings whose
    length ratio makes a >=0.5 similarity impossible score 0 outright, and
    the distance is capped at ``min_len`` so the result stays in [0, 1].
    (Values below 0.5 are mapped to the property's ``low`` by
    ``Property.compare_probability`` regardless, so the early-exit is
    behaviorally exact.)
    """

    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        v1, v2 = _utf16_expand(v1), _utf16_expand(v2)
        shorter = min(len(v1), len(v2))
        longer = max(len(v1), len(v2))
        if shorter == 0:
            return 0.0
        # distance >= longer - shorter; if that alone drops sim below 0.5 the
        # property maps to `low` anyway.
        if (longer - shorter) * 2 > shorter:
            return 0.0
        native = _native_module()
        if native is not None:
            return native.lev_sim(v1, v2)
        dist = min(levenshtein_distance(v1, v2, limit=shorter), shorter)
        return 1.0 - (dist / shorter)


class WeightedLevenshtein(Comparator):
    """Levenshtein with per-class character weights (digits weigh more).

    Duke's WeightedLevenshtein makes edits to digits more expensive than
    edits to letters (useful for id-ish fields).  Weights are configurable
    via params ``digit-weight``, ``letter-weight``, ``other-weight``.
    """

    is_tokenized = True

    def __init__(self):
        self.digit_weight = 2.0
        self.letter_weight = 1.0
        self.other_weight = 1.0

    def _weight(self, ch: str) -> float:
        if ch.isdigit():
            return self.digit_weight
        if ch.isalpha():
            return self.letter_weight
        return self.other_weight

    def _distance(self, s1: str, s2: str) -> float:
        n1, n2 = len(s1), len(s2)
        prev = [0.0] * (n2 + 1)
        for j in range(1, n2 + 1):
            prev[j] = prev[j - 1] + self._weight(s2[j - 1])
        for i in range(1, n1 + 1):
            w1 = self._weight(s1[i - 1])
            cur = [prev[0] + w1] + [0.0] * n2
            for j in range(1, n2 + 1):
                w2 = self._weight(s2[j - 1])
                sub = 0.0 if s1[i - 1] == s2[j - 1] else max(w1, w2)
                cur[j] = min(prev[j] + w1, cur[j - 1] + w2, prev[j - 1] + sub)
            prev = cur
        return prev[n2]

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        v1, v2 = _utf16_expand(v1), _utf16_expand(v2)
        shorter = min(len(v1), len(v2))
        if shorter == 0:
            return 0.0
        native = _native_module()
        # native classifies characters by ASCII class only, so non-ASCII
        # values (where isdigit/isalpha diverge) stay on the Python path
        if native is not None and v1.isascii() and v2.isascii():
            return native.weighted_lev(v1, v2, self.digit_weight,
                                       self.letter_weight, self.other_weight)
        # weighted distance over *unweighted* min length: edits to heavy
        # characters (digits) genuinely cost more similarity
        dist = min(self._distance(v1, v2), float(shorter))
        return 1.0 - (dist / shorter)


def _jaro(s1: str, s2: str) -> float:
    n1, n2 = len(s1), len(s2)
    if n1 == 0 or n2 == 0:
        return 0.0
    window = max(max(n1, n2) // 2 - 1, 0)
    matched2 = [False] * n2
    matches = 0
    m1: List[str] = []
    for i, c in enumerate(s1):
        lo = max(0, i - window)
        hi = min(n2, i + window + 1)
        for j in range(lo, hi):
            if not matched2[j] and s2[j] == c:
                matched2[j] = True
                matches += 1
                m1.append(c)
                break
    if matches == 0:
        return 0.0
    m2 = [s2[j] for j in range(n2) if matched2[j]]
    transpositions = sum(1 for a, b in zip(m1, m2) if a != b) // 2
    m = float(matches)
    return (m / n1 + m / n2 + (m - transpositions) / m) / 3.0


class JaroWinkler(Comparator):
    """Jaro-Winkler similarity (prefix scale 0.1, max prefix 4, boost 0.7)."""

    is_tokenized = False

    def __init__(self):
        self.prefix_scale = 0.1
        self.boost_threshold = 0.7
        self.max_prefix = 4

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        v1, v2 = _utf16_expand(v1), _utf16_expand(v2)
        native = _native_module()
        if native is not None:
            return native.jaro_winkler(v1, v2, self.prefix_scale,
                                       self.boost_threshold, self.max_prefix)
        j = _jaro(v1, v2)
        if j < self.boost_threshold:
            return j
        prefix = 0
        for a, b in zip(v1, v2):
            if a != b or prefix == self.max_prefix:
                break
            prefix += 1
        return j + prefix * self.prefix_scale * (1.0 - j)


class JaroWinklerTokenized(Comparator):
    """Monge-Elkan-style tokenized Jaro-Winkler.

    Splits on whitespace and scores each token of the shorter token list
    against its best match in the other, averaging the result (the shape of
    Duke's JaroWinklerTokenized).
    """

    is_tokenized = True

    def __init__(self):
        self._jw = JaroWinkler()

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        t1 = v1.split()
        t2 = v2.split()
        if not t1 or not t2:
            return 0.0
        if len(t1) > len(t2):
            t1, t2 = t2, t1
        total = 0.0
        for a in t1:
            total += max(self._jw.compare(a, b) for b in t2)
        return total / len(t1)


def qgrams(value: str, q: int) -> set:
    if len(value) < q:
        return {value} if value else set()
    return {value[i : i + q] for i in range(len(value) - q + 1)}


class QGram(Comparator):
    """q-gram set similarity; formula one of overlap|jaccard|dice (default overlap)."""

    is_tokenized = True

    def __init__(self):
        self.q = 2
        self.formula = "overlap"

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        g1 = qgrams(v1, self.q)
        g2 = qgrams(v2, self.q)
        if not g1 or not g2:
            return 0.0
        common = len(g1 & g2)
        if self.formula == "jaccard":
            return common / (len(g1) + len(g2) - common)
        if self.formula == "dice":
            return 2.0 * common / (len(g1) + len(g2))
        return common / min(len(g1), len(g2))


class JaccardIndex(Comparator):
    """Whitespace-token Jaccard index."""

    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        t1 = set(v1.split())
        t2 = set(v2.split())
        if not t1 or not t2:
            return 0.0
        inter = len(t1 & t2)
        union = len(t1) + len(t2) - inter
        return inter / union


class DiceCoefficient(Comparator):
    """Whitespace-token Dice coefficient."""

    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        t1 = set(v1.split())
        t2 = set(v2.split())
        if not t1 or not t2:
            return 0.0
        return 2.0 * len(t1 & t2) / (len(t1) + len(t2))


class Exact(Comparator):
    is_tokenized = False

    def compare(self, v1: str, v2: str) -> float:
        return 1.0 if v1 == v2 else 0.0


class Different(Comparator):
    """Inverse of Exact: evidence that two records differ when values equal."""

    is_tokenized = False

    def compare(self, v1: str, v2: str) -> float:
        return 0.0 if v1 == v2 else 1.0


class Numeric(Comparator):
    """Ratio of two numbers, cut off below ``min-ratio``.

    Configured in the reference demo config with ``min-ratio`` 0.7
    (testdukeconfig.xml:17-20).  Non-numeric values are neutral (0.5, like a
    missing comparator); values of opposite sign or zero/nonzero score 0.
    """

    is_tokenized = False

    def __init__(self):
        self.min_ratio = 0.0

    def compare(self, v1: str, v2: str) -> float:
        try:
            d1 = float(v1)
            d2 = float(v2)
        except (TypeError, ValueError):
            return 0.5
        if math.isnan(d1) or math.isnan(d2) or math.isinf(d1) or math.isinf(d2):
            return 0.5
        if d1 == d2:
            return 1.0
        if d1 == 0.0 or d2 == 0.0 or (d1 < 0.0) != (d2 < 0.0):
            return 0.0
        d1, d2 = abs(d1), abs(d2)
        ratio = min(d1, d2) / max(d1, d2)
        if ratio < self.min_ratio:
            return 0.0
        return ratio


_NAME_SPLIT_RE = re.compile(r"[\s]+")


class PersonName(Comparator):
    """Person-name similarity: token reordering, initials, per-token edit distance."""

    is_tokenized = True

    def __init__(self):
        self._lev = Levenshtein()

    def _token_sim(self, a: str, b: str) -> float:
        if a == b:
            return 1.0
        # initial vs full name: "j" ~ "john"
        if len(a) == 1 and b.startswith(a):
            return 0.8
        if len(b) == 1 and a.startswith(b):
            return 0.8
        return self._lev.compare(a, b)

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        t1 = _NAME_SPLIT_RE.split(v1.strip().lower())
        t2 = _NAME_SPLIT_RE.split(v2.strip().lower())
        t1 = [t for t in t1 if t]
        t2 = [t for t in t2 if t]
        if not t1 or not t2:
            return 0.0
        if sorted(t1) == sorted(t2):
            return 0.95  # same tokens, different order
        if len(t1) > len(t2):
            t1, t2 = t2, t1
        used = [False] * len(t2)
        total = 0.0
        for a in t1:
            best, best_j = 0.0, -1
            for j, b in enumerate(t2):
                if used[j]:
                    continue
                s = self._token_sim(a, b)
                if s > best:
                    best, best_j = s, j
            if best_j >= 0:
                used[best_j] = True
            total += best
        # average best-match score over the shorter name, discounted by the
        # token-count mismatch (sqrt so one extra middle name isn't fatal)
        return (total / len(t1)) * math.sqrt(len(t1) / len(t2))


def soundex(value: str) -> str:
    """Classic American Soundex code (letter + 3 digits)."""
    value = "".join(ch for ch in value.upper() if ch.isalpha())
    if not value:
        return ""
    codes = {
        **dict.fromkeys("BFPV", "1"),
        **dict.fromkeys("CGJKQSXZ", "2"),
        **dict.fromkeys("DT", "3"),
        "L": "4",
        **dict.fromkeys("MN", "5"),
        "R": "6",
    }
    first = value[0]
    out = [first]
    prev = codes.get(first, "")
    for ch in value[1:]:
        code = codes.get(ch, "")
        if ch in "HW":
            continue  # H/W do not break runs
        if code and code != prev:
            out.append(code)
            if len(out) == 4:
                break
        prev = code
    return "".join(out).ljust(4, "0")


class Soundex(Comparator):
    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        return 0.9 if soundex(v1) == soundex(v2) and soundex(v1) else 0.0


def metaphone(value: str) -> str:
    """Simplified Metaphone phonetic code (covers the common English rules)."""
    v = "".join(ch for ch in value.upper() if ch.isalpha())
    if not v:
        return ""
    # initial-letter exceptions
    for prefix, repl in (("AE", "E"), ("GN", "N"), ("KN", "N"), ("PN", "N"),
                         ("WR", "R"), ("X", "S"), ("WH", "W")):
        if v.startswith(prefix):
            v = repl + v[len(prefix):]
            break
    out = []
    i = 0
    n = len(v)
    vowels = "AEIOU"
    # "\0" as the out-of-bounds sentinel: unlike "", it is never a member of
    # the character-class strings tested below
    while i < n:
        c = v[i]
        nxt = v[i + 1] if i + 1 < n else "\0"
        prv = v[i - 1] if i > 0 else "\0"
        if c in vowels:
            if i == 0:
                out.append(c)
        elif c == "B":
            if not (i == n - 1 and prv == "M"):
                out.append("B")
        elif c == "C":
            if nxt == "H":
                out.append("X")
                i += 1
            elif nxt in "IEY":
                out.append("S")
            else:
                out.append("K")
        elif c == "D":
            if nxt == "G" and i + 2 < n and v[i + 2] in "EIY":
                out.append("J")
                i += 2
            else:
                out.append("T")
        elif c == "G":
            if nxt == "H":
                if i + 2 >= n or v[i + 2] in vowels:
                    out.append("K")
                i += 1
            elif nxt in "IEY":
                out.append("J")
            else:
                out.append("K")
        elif c == "H":
            if prv in vowels and nxt not in vowels:
                pass
            else:
                out.append("H")
        elif c in "FJLMNR":
            out.append(c)
        elif c == "K":
            if prv != "C":
                out.append("K")
        elif c == "P":
            if nxt == "H":
                out.append("F")
                i += 1
            else:
                out.append("P")
        elif c == "Q":
            out.append("K")
        elif c == "S":
            if nxt == "H":
                out.append("X")
                i += 1
            elif nxt == "I" and i + 2 < n and v[i + 2] in "OA":
                out.append("X")
            else:
                out.append("S")
        elif c == "T":
            if nxt == "H":
                out.append("0")
                i += 1
            elif nxt == "I" and i + 2 < n and v[i + 2] in "OA":
                out.append("X")
            else:
                out.append("T")
        elif c == "V":
            out.append("F")
        elif c == "W":
            if nxt in vowels:
                out.append("W")
        elif c == "X":
            out.append("KS")
        elif c == "Y":
            if nxt in vowels:
                out.append("Y")
        elif c == "Z":
            out.append("S")
        i += 1
    # collapse doubled codes
    code = []
    for ch in "".join(out):
        if not code or code[-1] != ch:
            code.append(ch)
    return "".join(code)


class Metaphone(Comparator):
    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        m1, m2 = metaphone(v1), metaphone(v2)
        return 0.9 if m1 and m1 == m2 else 0.0


def norphone(value: str) -> str:
    """Norphone-style phonetic code for Norwegian names.

    Follows the published Norphone rule set (Garshol): silent H/D endings,
    AA->A, C->K, W->V, PH->F, TH->T, SKJ/KJ/TJ->X(sh-sound), etc.
    """
    v = "".join(ch for ch in value.upper() if ch.isalpha() or ch in "ÆØÅ")
    if not v:
        return ""
    subs = [
        ("AA", "Å"), ("PH", "F"), ("TH", "T"), ("DT", "T"), ("CH", "K"),
        ("CK", "K"), ("GJ", "J"), ("GH", "K"), ("HJ", "J"), ("HG", "K"),
        ("LD", "L"), ("ND", "N"), ("RD", "R"), ("SKJ", "X"), ("SJ", "X"),
        ("KJ", "X"), ("TJ", "X"), ("QU", "KV"),
    ]
    for a, b in subs:
        v = v.replace(a, b)
    v = v.replace("C", "K").replace("W", "V").replace("Z", "S").replace("Q", "K")
    # drop non-initial vowels, collapse runs
    vowels = "AEIOUYÆØÅ"
    out = [v[0]]
    for ch in v[1:]:
        if ch in vowels:
            continue
        if out[-1] != ch:
            out.append(ch)
    return "".join(out)


class Norphone(Comparator):
    is_tokenized = True

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        n1, n2 = norphone(v1), norphone(v2)
        return 0.9 if n1 and n1 == n2 else 0.0


_EARTH_RADIUS_M = 6371000.0


class Geoposition(Comparator):
    """Similarity of two 'lat,long' coordinates by haversine distance.

    Param ``max-distance`` (meters): sim falls linearly from 1 at distance 0
    to 0 at max-distance.  Referenced (but gated off) by the reference's
    blocking layer (IncrementalLuceneDatabase.java:461-463); fully supported
    here.
    """

    is_tokenized = False

    def __init__(self):
        self.max_distance = 0.0

    @staticmethod
    def _parse(v: str):
        parts = v.replace(";", ",").split(",")
        if len(parts) != 2:
            return None
        try:
            return math.radians(float(parts[0])), math.radians(float(parts[1]))
        except ValueError:
            return None

    def compare(self, v1: str, v2: str) -> float:
        p1 = self._parse(v1)
        p2 = self._parse(v2)
        if p1 is None or p2 is None:
            return 0.5
        (lat1, lon1), (lat2, lon2) = p1, p2
        dlat = lat2 - lat1
        dlon = lon2 - lon1
        a = math.sin(dlat / 2) ** 2 + math.cos(lat1) * math.cos(lat2) * math.sin(dlon / 2) ** 2
        dist = 2 * _EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(a)))
        if self.max_distance <= 0:
            return 1.0 if dist == 0 else 0.0
        return max(0.0, 1.0 - dist / self.max_distance)


class LongestCommonSubstring(Comparator):
    """Iterated longest-common-substring similarity (Duke's shape).

    Repeatedly removes the longest common substring of length >= ``minlen``
    and accumulates its length; similarity is the accumulated length over the
    length of the shorter input.
    """

    is_tokenized = True

    def __init__(self):
        self.minlen = 2

    @staticmethod
    def _lcs(s1: str, s2: str):
        best_len, best_i, best_j = 0, 0, 0
        prev = [0] * (len(s2) + 1)
        for i in range(1, len(s1) + 1):
            cur = [0] * (len(s2) + 1)
            for j in range(1, len(s2) + 1):
                if s1[i - 1] == s2[j - 1]:
                    cur[j] = prev[j - 1] + 1
                    if cur[j] > best_len:
                        best_len, best_i, best_j = cur[j], i, j
            prev = cur
        return best_len, best_i - best_len, best_j - best_len

    def compare(self, v1: str, v2: str) -> float:
        if v1 == v2:
            return 1.0
        shorter = min(len(v1), len(v2))
        if shorter == 0:
            return 0.0
        total = 0
        s1, s2 = v1, v2
        min_take = max(1, self.minlen)  # minlen<=0 would loop forever on length-0 LCS
        while True:
            length, i, j = self._lcs(s1, s2)
            if length < min_take:
                break
            total += length
            s1 = s1[:i] + s1[i + length :]
            s2 = s2[:j] + s2[j + length :]
        return min(1.0, total / shorter)


_REGISTRY: Dict[str, Type[Comparator]] = {}


def register_comparator(cls: Type[Comparator], *names: str) -> None:
    for name in names:
        _REGISTRY[name] = cls


_DUKE = "no.priv.garshol.duke.comparators."
register_comparator(Levenshtein, _DUKE + "Levenshtein", "Levenshtein", "levenshtein")
register_comparator(
    WeightedLevenshtein, _DUKE + "WeightedLevenshtein", "WeightedLevenshtein", "weighted-levenshtein"
)
register_comparator(JaroWinkler, _DUKE + "JaroWinkler", "JaroWinkler", "jaro-winkler")
register_comparator(
    JaroWinklerTokenized,
    _DUKE + "JaroWinklerTokenized",
    "JaroWinklerTokenized",
    "jaro-winkler-tokenized",
)
register_comparator(QGram, _DUKE + "QGramComparator", "QGramComparator", "qgram")
register_comparator(
    JaccardIndex, _DUKE + "JaccardIndexComparator", "JaccardIndexComparator", "jaccard"
)
register_comparator(
    DiceCoefficient,
    _DUKE + "DiceCoefficientComparator",
    "DiceCoefficientComparator",
    "dice",
)
register_comparator(Exact, _DUKE + "ExactComparator", "ExactComparator", "exact")
register_comparator(
    Different, _DUKE + "DifferentComparator", "DifferentComparator", "different"
)
register_comparator(
    Numeric, _DUKE + "NumericComparator", "NumericComparator", "numeric"
)
register_comparator(
    PersonName, _DUKE + "PersonNameComparator", "PersonNameComparator", "person-name"
)
register_comparator(
    Soundex, _DUKE + "SoundexComparator", "SoundexComparator", "soundex"
)
register_comparator(
    Metaphone, _DUKE + "MetaphoneComparator", "MetaphoneComparator", "metaphone"
)
register_comparator(
    Norphone, _DUKE + "NorphoneComparator", "NorphoneComparator", "norphone"
)
register_comparator(
    Geoposition, _DUKE + "GeopositionComparator", "GeopositionComparator", "geoposition"
)
register_comparator(
    LongestCommonSubstring,
    _DUKE + "LongestCommonSubstringComparator",
    "LongestCommonSubstringComparator",
    "longest-common-substring",
)


def make_comparator(name: str) -> Comparator:
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"Unknown comparator '{name}'. Known comparators: {sorted(_REGISTRY)}"
        ) from None
    return cls()


def comparator_class(name: str) -> Type[Comparator]:
    return _REGISTRY[name]


def has_comparator(name: str) -> bool:
    return name in _REGISTRY


def available_comparators() -> Sequence[str]:
    return sorted(_REGISTRY)
