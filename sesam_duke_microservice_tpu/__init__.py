"""sesam-duke-microservice_tpu — a TPU-native record-matching framework.

A ground-up reimplementation of the capabilities of the
``sesam-io/sesam-duke-microservice`` reference (an incremental deduplication /
record-linkage REST microservice wrapping the Duke 1.2 entity-matching engine),
redesigned TPU-first: the matching hot loop (candidate blocking -> per-property
string similarity -> naive-Bayes combination) runs as batched JAX/XLA/Pallas
programs over HBM-resident padded token tensors, sharded across a
``jax.sharding.Mesh`` for multi-chip scale.

Subpackages
-----------
core      Records, properties, cleaners, comparator oracles, config parsing.
ops       JAX/Pallas device kernels (tokenize, levenshtein, jaro-winkler, ...).
index     Candidate blocking backends (device top-k, host inverted index).
engine    The match processor, listeners and device matcher.
links     Link persistence (in-memory / sqlite) with `?since=` feeds.
service   The HTTP frontend reproducing the reference REST surface.
parallel  Mesh construction and sharded retrieval (shard_map + collectives).
models    Flax record-encoder (embedding-ANN blocking) + training.
"""

__version__ = "0.1.0"

# DUKE_LOCKCHECK=1 runtime lock-order sanitizer: must install before any
# package module creates a lock, so it lives at the top of the package
# import (no-op — not even a wrapper — when the flag is unset)
from .utils import lockcheck as _lockcheck  # noqa: E402

_lockcheck.install_if_enabled()
