"""Link model and link-database interface.

Re-expresses the Duke 1.2 link API surface the reference drives
(``Link``/``LinkStatus``/``LinkDatabase`` — App.java:63-65,997-1000;
SinceAwareInMemoryLinkDatabase.java) in Python.  A link records that two
record ids were inferred to (maybe) refer to the same entity; clients poll
changes incrementally by millisecond timestamp (``get_changes_since``,
served by GET /deduplication/:name?since=N — App.java:843).
"""

from __future__ import annotations

import enum
import threading
import time
from typing import List, Optional


class LinkStatus(enum.Enum):
    ASSERTED = "asserted"
    INFERRED = "inferred"
    UNKNOWN = "unknown"
    RETRACTED = "retracted"


class LinkKind(enum.Enum):
    DUPLICATE = "duplicate"
    MAYBE = "maybe"
    DIFFERENT = "different"


_last_millis = 0
_millis_lock = threading.Lock()


def now_millis() -> int:
    """Millisecond wall-clock, strictly monotonic per process.

    The reference stamps links with System.currentTimeMillis, so two updates
    to the same link within one millisecond are indistinguishable to a
    ``?since=`` poller.  Bumping by 1ms on collision keeps every change
    observable without altering the wire format.
    """
    global _last_millis
    with _millis_lock:
        now = int(time.time() * 1000)
        if now <= _last_millis:
            now = _last_millis + 1
        _last_millis = now
        return now


class Link:
    """An (id1, id2) pair with status/kind/confidence/timestamp.

    Ids are stored in sorted order so (a, b) and (b, a) are the same link
    (Duke's Link constructor normalizes the same way; the feed's ``_id`` is
    ``id1 + "_" + id2`` — App.java:759).
    """

    __slots__ = ("id1", "id2", "status", "kind", "confidence", "timestamp")

    def __init__(self, id1: str, id2: str, status: LinkStatus, kind: LinkKind,
                 confidence: float, timestamp: Optional[int] = None):
        if id1 > id2:
            id1, id2 = id2, id1
        self.id1 = id1
        self.id2 = id2
        self.status = status
        self.kind = kind
        self.confidence = float(confidence)
        self.timestamp = now_millis() if timestamp is None else int(timestamp)

    def key(self):
        return (self.id1, self.id2)

    def retract(self) -> None:
        """Mark the link retracted and touch the timestamp (Duke Link.retract;
        driven at App.java:997-1000)."""
        self.status = LinkStatus.RETRACTED
        self.timestamp = now_millis()

    def copy(self) -> "Link":
        return Link(self.id1, self.id2, self.status, self.kind,
                    self.confidence, self.timestamp)

    def __repr__(self) -> str:
        return (f"Link({self.id1!r}, {self.id2!r}, {self.status.value}, "
                f"{self.kind.value}, {self.confidence:.4f}, ts={self.timestamp})")


class LinkDatabase:
    """Interface: assert/retrieve links, incremental change feed."""

    def assert_link(self, link: Link) -> None:
        raise NotImplementedError

    def assert_links(self, links: List[Link]) -> None:
        """Assert a whole batch of links in arrival order.

        The listener chain collects one batch's match events and persists
        them here in a single call — the durable backend turns this into
        ONE transaction (``executemany``) instead of a query+commit per
        link, which dominated the persist phase on match-heavy batches.
        This default keeps tiny custom backends working.
        """
        for link in links:
            self.assert_link(link)

    def get_all_links_for(self, record_id: str) -> List[Link]:
        raise NotImplementedError

    def get_links_for_ids(self, record_ids) -> List[Link]:
        """All links touching any of ``record_ids`` — one batched lookup.

        The one-to-one flush needs every existing link for a whole batch of
        records; per-pair ``get_all_links_for`` calls would dominate
        ``batch_done`` latency on large linkage batches.  Backends override
        with a single scan/query; this default keeps tiny custom backends
        working.
        """
        ids = set(record_ids)
        seen = {}
        for rid in ids:
            for link in self.get_all_links_for(rid):
                seen[link.key()] = link
        return list(seen.values())

    def get_all_links(self) -> List[Link]:
        raise NotImplementedError

    def count(self) -> int:
        """Total link rows (asserted + retracted) — the /stats and
        /metrics per-workload row count.  Backends override with an O(1)
        counter or a COUNT(*) query; this default keeps tiny custom
        backends working."""
        return len(self.get_all_links())

    def get_changes_since(self, since: int) -> List[Link]:
        raise NotImplementedError

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        """First ``limit`` changes after ``since`` in (timestamp, id1, id2)
        order — EXTENDED to include every further link sharing the page's
        final timestamp, so a caller paging with ``since = page[-1]
        .timestamp`` never skips a tied row.  Timestamps are unique for
        links written by this process (links.base.now_millis is strictly
        monotonic), so the extension only triggers on data imported from
        elsewhere.  Backends override with a bounded query; this default
        keeps tiny custom backends working (it materializes the full
        tail)."""
        changes = self.get_changes_since(since)
        if limit <= 0 or len(changes) <= limit:
            return changes
        cut = limit
        last_ts = changes[limit - 1].timestamp
        while cut < len(changes) and changes[cut].timestamp == last_ts:
            cut += 1
        return changes[:cut]

    def commit(self) -> None:
        pass

    def drain(self) -> None:
        """Block until every buffered/asynchronous write is durably
        applied.  Synchronous backends have nothing pending — only the
        write-behind wrapper overrides; callers needing the barrier
        (snapshot save, benchmarks) call it unconditionally."""

    @property
    def flush_error(self):
        """The latched background-flush failure, or None.  Synchronous
        backends can never latch; the write-behind wrapper overrides.
        Surfaced by ``/readyz`` (unready) and ``/healthz`` so a dead
        persistence thread is visible to orchestrators before a read
        drains into it (ISSUE 8 satellite)."""
        return None

    def close(self) -> None:
        pass


# Idempotence tolerance for repeated asserts of an unchanged link
# (SinceAwareInMemoryLinkDatabase.java:22-24)
CONFIDENCE_EPSILON = 1e-6


def is_same_assertion(old: Link, new: Link) -> bool:
    return (
        old.status == new.status
        and old.kind == new.kind
        and abs(old.confidence - new.confidence) < CONFIDENCE_EPSILON
    )
