from ..telemetry.env import env_flag
from .base import Link, LinkStatus, LinkKind, LinkDatabase
from .memory import InMemoryLinkDatabase
from .replica import PublishingLinkDatabase, ReplicaLinkDatabase
from .sqlite import SqliteLinkDatabase
from .write_behind import WriteBehindLinkDatabase

__all__ = [
    "Link",
    "LinkStatus",
    "LinkKind",
    "LinkDatabase",
    "InMemoryLinkDatabase",
    "PublishingLinkDatabase",
    "ReplicaLinkDatabase",
    "SqliteLinkDatabase",
    "WriteBehindLinkDatabase",
]


def create_link_database(link_database_type: str, data_folder=None,
                         is_record_linkage: bool = False) -> LinkDatabase:
    """Factory mirroring App.java:566-611: 'h2' (durable; SQLite here) or
    'in-memory'.

    Unless ``DUKE_WRITE_BEHIND=0``, the DURABLE backend is wrapped in
    ``WriteBehindLinkDatabase`` so each batch's flush transaction
    overlaps the next microbatch's encode phase; every row-returning
    read drains first, so feed and lookup semantics are unchanged
    (links.write_behind).  The in-memory backend is never wrapped —
    its writes are microsecond list appends with nothing to overlap,
    so the flusher thread and drain barriers would be pure overhead.
    """
    import os

    if link_database_type == "in-memory":
        return InMemoryLinkDatabase()
    if link_database_type == "h2":
        if data_folder is None:
            return InMemoryLinkDatabase()
        name = "recordlinkdatabase" if is_record_linkage else "linkdatabase"
        os.makedirs(data_folder, exist_ok=True)
        db = SqliteLinkDatabase(os.path.join(data_folder, name + ".sqlite"))
        if not env_flag("DUKE_WRITE_BEHIND", True):
            return db
        return WriteBehindLinkDatabase(db)
    raise ValueError(f"Got an unknown 'link-database-type' value: '{link_database_type}'")
