import logging

from ..telemetry.env import env_flag
from .base import Link, LinkStatus, LinkKind, LinkDatabase
from .journal import LinkJournal
from .memory import InMemoryLinkDatabase
from .replica import PublishingLinkDatabase, ReplicaLinkDatabase
from .sqlite import SqliteLinkDatabase
from .write_behind import WriteBehindLinkDatabase

__all__ = [
    "Link",
    "LinkStatus",
    "LinkKind",
    "LinkDatabase",
    "LinkJournal",
    "InMemoryLinkDatabase",
    "PublishingLinkDatabase",
    "ReplicaLinkDatabase",
    "SqliteLinkDatabase",
    "WriteBehindLinkDatabase",
]

logger = logging.getLogger("links")


def create_link_database(link_database_type: str, data_folder=None,
                         is_record_linkage: bool = False) -> LinkDatabase:
    """Factory mirroring App.java:566-611: 'h2' (durable; SQLite here) or
    'in-memory'.

    Unless ``DUKE_WRITE_BEHIND=0``, the DURABLE backend is wrapped in
    ``WriteBehindLinkDatabase`` so each batch's flush transaction
    overlaps the next microbatch's encode phase; every row-returning
    read drains first, so feed and lookup semantics are unchanged
    (links.write_behind).  Unless ``DUKE_JOURNAL=0``, the wrapper
    additionally journals every sealed batch durably BEFORE it is acked
    (links.journal) and replays journaled-but-unapplied batches here at
    open — startup recovery, flagged to ``/readyz`` as ``recovering``
    while it runs.  The in-memory backend is never wrapped — its writes
    are microsecond list appends with nothing to overlap, so the flusher
    thread and drain barriers would be pure overhead.
    """
    import os

    from . import journal as journal_mod

    if link_database_type == "in-memory":
        return InMemoryLinkDatabase()
    if link_database_type == "h2":
        if data_folder is None:
            return InMemoryLinkDatabase()
        name = "recordlinkdatabase" if is_record_linkage else "linkdatabase"
        os.makedirs(data_folder, exist_ok=True)
        journal_path = os.path.join(data_folder, name + ".journal")

        def warn_stranded(why: str) -> None:
            # a journal left by an earlier journaled run may hold acked
            # batches the flusher never applied; with journaling off we
            # deliberately leave it untouched (it replays when
            # DUKE_JOURNAL is re-enabled — the opt-out legs must pin the
            # legacy path exactly), but stranding durable acked data
            # must never be silent
            try:
                size = os.path.getsize(journal_path)
            except OSError:
                return
            if size > 0:
                logger.warning(
                    "%s: existing link journal %s (%d bytes) is NOT "
                    "being replayed (%s); any acked-but-unapplied "
                    "batches in it stay stranded until the service "
                    "restarts with DUKE_JOURNAL=1",
                    data_folder, journal_path, size, why,
                )

        db = SqliteLinkDatabase(os.path.join(data_folder, name + ".sqlite"))
        if not env_flag("DUKE_WRITE_BEHIND", True):
            # synchronous writes: durable before the ack by construction,
            # nothing for a journal to add
            warn_stranded("DUKE_WRITE_BEHIND=0")
            return db
        if not env_flag("DUKE_JOURNAL", True):
            # the enforced caveat (ISSUE 10): journal-less write-behind
            # acks batches still in volatile memory — in-memory link-DB
            # semantics for the window until the background flush lands.
            # Said out loud at startup so the trade-off is a choice, not
            # a surprise; an existing journal file is left untouched (it
            # replays when DUKE_JOURNAL is re-enabled).
            logger.warning(
                "DUKE_JOURNAL=0: write-behind link batches for %s are "
                "acknowledged before they are durable; a crash in that "
                "window permanently loses acked links", data_folder,
            )
            warn_stranded("DUKE_JOURNAL=0")
            return WriteBehindLinkDatabase(db)
        journal = LinkJournal(journal_path)
        wrapped = WriteBehindLinkDatabase(db, journal=journal)
        # recovery scoped to this workload's data folder: with N serving
        # groups in one process (federation), one group's replay flips
        # only readiness probes watching ITS folder to "recovering".
        # DUKE_RECOVERY_OVERLAP (default on, ISSUE 15) replays the
        # backlog on a background thread so feed/monitoring reads serve
        # the committed prefix immediately (X-Recovering header) while
        # writes stay fenced until replay completes; =0 pins the legacy
        # serial recovery exactly (the whole build blocks here).
        if env_flag("DUKE_RECOVERY_OVERLAP", True):
            wrapped.recover_async(scope=data_folder)
        else:
            with journal_mod.recovery_in_progress(data_folder):
                wrapped.recover()
        return wrapped
    raise ValueError(f"Got an unknown 'link-database-type' value: '{link_database_type}'")
