from .base import Link, LinkStatus, LinkKind, LinkDatabase
from .memory import InMemoryLinkDatabase
from .sqlite import SqliteLinkDatabase

__all__ = [
    "Link",
    "LinkStatus",
    "LinkKind",
    "LinkDatabase",
    "InMemoryLinkDatabase",
    "SqliteLinkDatabase",
]


def create_link_database(link_database_type: str, data_folder=None,
                         is_record_linkage: bool = False) -> LinkDatabase:
    """Factory mirroring App.java:566-611: 'h2' (durable; SQLite here) or
    'in-memory'."""
    import os

    if link_database_type == "in-memory":
        return InMemoryLinkDatabase()
    if link_database_type == "h2":
        if data_folder is None:
            return InMemoryLinkDatabase()
        name = "recordlinkdatabase" if is_record_linkage else "linkdatabase"
        os.makedirs(data_folder, exist_ok=True)
        return SqliteLinkDatabase(os.path.join(data_folder, name + ".sqlite"))
    raise ValueError(f"Got an unknown 'link-database-type' value: '{link_database_type}'")
