"""Durable link journal — the redo log behind crash-consistent ingest.

PR 3's write-behind wrapper acknowledges HTTP 200 while the batch's link
upserts are still in volatile memory; a crash between the ack and the
background flush silently and permanently lost confirmed matches (the
reference never has this window: its H2 link DB commits synchronously,
App.java:566-611).  ``LinkJournal`` closes the window without giving up
the write-behind overlap: the sealed batch is appended here — durably,
per the configured sync policy — *before* the ack, turning the
background flusher into a redo-log applier.  On restart, recovery
(``WriteBehindLinkDatabase.recover``) replays any journaled batch the
flusher never applied through the idempotent ``assert_links`` path, so
an acked batch survives a crash at ANY point after the append.

On-disk format (append-only, length-framed, CRC-guarded)::

    frame    := kind(1) seq(u64 LE) length(u32 LE) crc(u32 LE) payload
    kind     := b"B" (sealed batch) | b"A" (applied watermark)
    payload  := JSON array of 6-element link rows (links.replica
                encode_link order: id1, id2, status, kind, confidence,
                timestamp); empty for b"A" frames
    crc      := crc32 over kind+seq+length+payload

``b"B"`` frames carry a strictly monotonic batch sequence; ``b"A"``
frames advance the applied watermark (appended by the flusher AFTER the
durable store committed the batch, never synced — losing one only means
re-replaying an applied batch, which the idempotent assert absorbs).
The startup scan truncates a torn tail (a crash mid-append) at the first
incomplete or CRC-failing frame: counted in
``duke_journal_torn_tails_total`` and logged, never fatal — everything
before the tear is intact by construction.  Once the watermark catches
the head, the journal compacts back to zero bytes (bounded disk, and a
cleanly-shut-down service restarts with nothing to replay).

Sync policy (``DUKE_JOURNAL_SYNC``): ``fsync`` (data + metadata),
``fdatasync`` (data only — the default; the file is preallocated-free
but append-mostly, and fdatasync bounds the loss window identically for
our replay purposes), or ``none`` (OS page cache only: a *process* crash
loses nothing, an OS/power crash can lose the tail — still strictly
better than no journal).  bench.py's ``durability`` section measures the
policies so the default is a number, not a guess.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import threading
import zlib
from typing import List, Optional, Sequence, Tuple

from .. import telemetry
from ..telemetry.env import env_str
from ..utils import faults

logger = logging.getLogger("links-journal")

_PREFIX = struct.Struct("<cQI")  # kind, seq, payload length
_CRC = struct.Struct("<I")
_HDR_BYTES = _PREFIX.size + _CRC.size
_KIND_BATCH = b"B"
_KIND_APPLIED = b"A"
# corruption guard: no sane batch payload approaches this, so a garbage
# length field is classified as a torn tail instead of a giant allocation
_MAX_FRAME_BYTES = 256 * 1024 * 1024
# compact (truncate to zero) once the watermark has caught the head and
# the file has grown past this — keeps steady-state disk bounded without
# paying a truncate per batch
_COMPACT_BYTES = 256 * 1024

SYNC_POLICIES = ("fsync", "fdatasync", "none")
DEFAULT_SYNC_POLICY = "fdatasync"


def sync_policy() -> str:
    """The configured ``DUKE_JOURNAL_SYNC`` policy (fail-to-default)."""
    raw = (env_str("DUKE_JOURNAL_SYNC") or DEFAULT_SYNC_POLICY).strip().lower()
    return raw if raw in SYNC_POLICIES else DEFAULT_SYNC_POLICY


# -- recovery visibility (consumed by /readyz) --------------------------------
#
# Scoped per journal owner (the workload's data folder) rather than one
# process-global counter: a federation harness runs N serving groups in
# one process, and one group's startup replay must flip only ITS OWN
# group's /readyz to "recovering" — not every group's (ISSUE 14
# satellite).  The anonymous scope ("") is process-wide: it matches
# every query, preserving the legacy no-argument behavior for callers
# that have no scope to name.

_RECOVERY_LOCK = threading.Lock()
_recovering: dict = {}  # scope -> entry depth; guarded by: _RECOVERY_LOCK [writes]


def recovery_begin(scope: str = "") -> None:
    """Mark startup journal replay active for ``scope``.  Split from the
    context manager so overlapped recovery (ISSUE 15) can enter the
    scope on the CONSTRUCTING thread — before the factory returns a
    serving wrapper — and exit it from the background replay thread; a
    readiness probe can then never observe the gap between the wrapper
    existing and the replay thread having started."""
    with _RECOVERY_LOCK:
        _recovering[scope] = _recovering.get(scope, 0) + 1


def recovery_end(scope: str = "") -> None:
    with _RECOVERY_LOCK:
        depth = _recovering.get(scope, 0) - 1
        if depth <= 0:
            _recovering.pop(scope, None)
        else:
            _recovering[scope] = depth


@contextlib.contextmanager
def recovery_in_progress(scope: str = ""):
    """Marks startup journal replay as active for ``scope`` (the owning
    workload's data folder; "" = process-wide); ``/readyz`` reports
    ``recovering`` until every entered context for a scope it watches
    exits."""
    recovery_begin(scope)
    try:
        yield
    finally:
        recovery_end(scope)


def recovery_active(scope: Optional[str] = None) -> bool:
    """Whether a journal replay is running — for ``scope`` (plus the
    anonymous process-wide scope), or anywhere when ``scope`` is None.
    Lock-free read: membership checks on the dict are GIL-atomic and the
    probe path (/readyz) must never contend with a replay."""
    active = _recovering
    if scope is None:
        return bool(active)
    return scope in active or "" in active


def _frame(kind: bytes, seq: int, payload: bytes) -> bytes:
    prefix = _PREFIX.pack(kind, seq, len(payload))
    return prefix + _CRC.pack(zlib.crc32(prefix + payload)) + payload


# streaming read granularity: one pread per chunk, carry buffer compacts
# back to at most one in-progress frame + a chunk
_READ_CHUNK = 1 << 20


class _TornTail(Exception):
    """Internal: frame walk hit a torn/corrupt tail.  ``good`` is the
    byte offset of the last intact frame boundary."""

    def __init__(self, reason: str, good: int):
        super().__init__(reason)
        self.reason = reason
        self.good = good


def _iter_frames(fd: int, end: int):
    """Yield ``(kind, seq, payload, end_offset)`` for every intact frame
    in ``fd[0:end]``, streaming in bounded chunks — O(n) in file bytes
    with memory bounded by one frame + one read chunk, never the whole
    file (the old scan's ``buf += chunk`` whole-file accumulation was
    quadratic in the worst case and unbounded always).  Raises
    ``_TornTail`` at the first incomplete or CRC-failing frame; a clean
    EOF just stops."""
    buf = bytearray()
    base = 0  # file offset of buf[0]
    pos = 0   # parse cursor, relative to buf
    read_off = 0  # next file offset to pread

    def _fill(need: int) -> bool:
        # ensure buf holds >= need bytes past pos (or EOF); True if it does
        nonlocal read_off
        while len(buf) - pos < need and read_off < end:
            chunk = os.pread(fd, min(_READ_CHUNK, end - read_off), read_off)
            if not chunk:
                break  # file shorter than fstat said (concurrent truncate)
            buf.extend(chunk)
            read_off += len(chunk)
        return len(buf) - pos >= need

    while base + pos < end:
        # compact the consumed prefix so the carry buffer stays bounded
        if pos >= _READ_CHUNK:
            del buf[:pos]
            base += pos
            pos = 0
        good = base + pos
        if not _fill(_HDR_BYTES):
            raise _TornTail("incomplete frame header", good)
        kind, seq, length = _PREFIX.unpack_from(buf, pos)
        (crc,) = _CRC.unpack_from(buf, pos + _PREFIX.size)
        if kind not in (_KIND_BATCH, _KIND_APPLIED) or length > _MAX_FRAME_BYTES:
            raise _TornTail(
                f"corrupt frame header (kind={kind!r}, len={length})", good)
        if not _fill(_HDR_BYTES + length):
            raise _TornTail("incomplete frame payload", good)
        payload = bytes(buf[pos + _HDR_BYTES:pos + _HDR_BYTES + length])
        if zlib.crc32(bytes(buf[pos:pos + _PREFIX.size]) + payload) != crc:
            raise _TornTail("frame CRC mismatch", good)
        pos += _HDR_BYTES + length
        yield kind, seq, payload, base + pos


def _write_all(fd: int, data: bytes) -> None:
    """Write every byte or raise.  ``os.write`` may return a short count
    (ENOSPC mid-frame, signal) WITHOUT raising — treating that as the
    durability point would ack a batch whose frame the startup scan will
    truncate as a torn tail, silently reopening the loss window."""
    view = memoryview(data)
    while view:
        n = os.write(fd, view)
        if n <= 0:
            raise OSError(
                f"journal write made no progress ({len(view)} bytes left)")
        view = view[n:]


class LinkJournal:
    """Append-only redo log for sealed write-behind link batches.

    Thread model: ``append_batch`` runs on the ingest path (under the
    write-behind buffer's condition, itself under the workload lock),
    ``mark_applied`` on the background flusher, scrapes read the plain
    int counters lock-free.  ``self._lock`` serializes every file
    mutation; the only lock ever taken under it is the fault plan's
    injection counter (chaos runs only).
    """

    def __init__(self, path: str, sync: Optional[str] = None):
        self.path = path
        self._sync = sync if sync in SYNC_POLICIES else sync_policy()
        self._lock = threading.Lock()
        # writes (the close() -1 sentinel) serialize under the lock; the
        # fd VALUE is read lock-free by the pre-publication startup scan
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)  # guarded by: self._lock [writes]
        self._last_seq = 0  # guarded by: self._lock [writes]
        self._applied_seq = 0  # guarded by: self._lock [writes]
        # batches scanned at open with seq > the applied watermark, in
        # file order — recovery's replay set (cleared by unapplied())
        self._unapplied: List[Tuple[int, List]] = []  # guarded by: self._lock [writes]
        # lock-free scrape mirrors (plain ints; exact under self._lock)
        self.pending_batches = 0  # guarded by: self._lock [writes]
        self.size_bytes = 0  # guarded by: self._lock [writes]
        # compaction pins (retained()): >0 while a migration slice walks
        # the file, so mark_applied/compact cannot truncate mid-walk
        self._pins = 0  # guarded by: self._lock [writes]
        self._scan()

    # -- startup scan ---------------------------------------------------------

    def _scan(self) -> None:
        """Parse every frame via the streaming iterator (O(n) bytes,
        memory bounded by the UNAPPLIED batches — applied batches are
        pruned as their watermark frames stream past, so a large mostly-
        applied journal never materializes in RAM); truncate a torn/
        corrupt tail (counted, logged, never fatal) and collect unapplied
        batches for replay."""
        from collections import deque

        size = os.fstat(self._fd).st_size
        good = 0
        pending: deque = deque()  # (seq, rows), insertion = seq order
        applied = 0
        last = 0
        torn = None
        try:
            for kind, seq, payload, end in _iter_frames(self._fd, size):
                if kind == _KIND_BATCH:
                    try:
                        rows = json.loads(payload.decode("utf-8"))
                    except ValueError:
                        torn = "undecodable batch payload"
                        break
                    pending.append((seq, rows))
                    last = max(last, seq)
                else:
                    applied = max(applied, seq)
                    while pending and pending[0][0] <= applied:
                        pending.popleft()
                good = end
        except _TornTail as tear:
            torn, good = tear.reason, tear.good
        if torn is not None:
            telemetry.JOURNAL_TORN_TAILS.inc()  # dukecheck: ignore[DK502] startup scan only, never per-batch
            logger.warning(
                "truncating torn journal tail in %s at byte %d (%s; %d "
                "byte(s) dropped) — everything before the tear is intact",
                self.path, good, torn, size - good,
            )
            os.ftruncate(self._fd, good)
        with self._lock:
            self._last_seq = max(last, applied)
            self._applied_seq = applied
            self._unapplied = [(s, rows) for s, rows in pending
                               if s > applied]
            self.pending_batches = len(self._unapplied)
            self.size_bytes = good

    def unapplied(self) -> List[Tuple[int, List]]:
        """The startup scan's replay set: (seq, encoded rows) for every
        journaled batch past the applied watermark, in append order.
        Consumed once — recovery replays then marks each applied."""
        with self._lock:
            out, self._unapplied = self._unapplied, []
        return out

    # -- range-migration slice (ISSUE 14) -------------------------------------

    def head_seq(self) -> int:
        with self._lock:
            return self._last_seq

    def applied_watermark(self) -> int:
        with self._lock:
            return self._applied_seq

    @contextlib.contextmanager
    def retained(self):
        """Pin the journal against compaction for the duration — a live
        range migration streams ``batches_after`` from the file, and a
        concurrent flusher catching up to the head must not truncate the
        frames out from under the walk.  Reentrant (pin counted)."""
        with self._lock:
            self._pins += 1
        try:
            yield self
        finally:
            with self._lock:
                self._pins -= 1

    def batches_after(self, after_seq: int):
        """Stream ``(seq, encoded rows)`` for every journaled batch frame
        with seq > ``after_seq``, in append order — the range migration's
        replay-slice primitive (the caller filters rows to the moving
        digest range and applies them through the target's idempotent
        ``assert_links``).  Lock-free walk of the stable append-only
        prefix (same discipline as the pre-publication startup scan);
        call under ``retained()`` so compaction cannot truncate the
        frames mid-walk.  A torn tail ends the slice silently — frames
        past a tear are untrusted by construction and the startup scan
        owns counting/truncating them."""
        fd = self._fd
        if fd < 0:
            return
        size = os.fstat(fd).st_size
        try:
            for kind, seq, payload, _end in _iter_frames(fd, size):
                if kind != _KIND_BATCH or seq <= after_seq:
                    continue
                try:
                    rows = json.loads(payload.decode("utf-8"))
                except ValueError:
                    return
                yield seq, rows
        except _TornTail:
            return

    # -- append path (ingest thread) ------------------------------------------

    def append_batch(self, rows: Sequence) -> int:
        """Durably append one sealed batch; returns its sequence number.
        Called BEFORE the batch is acknowledged — this write (plus the
        configured sync) IS the durability point."""
        payload = json.dumps(rows, separators=(",", ":")).encode("utf-8")
        with self._lock:
            seq = self._last_seq + 1
            frame = _frame(_KIND_BATCH, seq, payload)
            plan = faults.active()
            if plan is not None and plan.crash_hit("mid_journal_write"):
                # torn-tail synthesis: half the frame reaches the disk,
                # then the process dies mid-write (no partial-write
                # cleanup can run — that is the point)
                os.write(self._fd, frame[: max(1, len(frame) // 2)])
                os.fsync(self._fd)
                plan.crash_now("mid_journal_write")
            _write_all(self._fd, frame)
            if self._sync == "fsync":
                os.fsync(self._fd)
            elif self._sync == "fdatasync":
                getattr(os, "fdatasync", os.fsync)(self._fd)
            self._last_seq = seq
            self.pending_batches = seq - self._applied_seq
            self.size_bytes += len(frame)
        return seq

    # -- apply path (background flusher) --------------------------------------

    def mark_applied(self, seq: int) -> None:
        """Advance the applied watermark past ``seq`` (called after the
        durable store committed the batch).  Unsynced by design: losing
        the marker re-replays an applied batch, which is idempotent.
        Compacts once the watermark catches the head."""
        with self._lock:
            if seq <= self._applied_seq:
                return
            frame = _frame(_KIND_APPLIED, seq, b"")
            _write_all(self._fd, frame)
            self._applied_seq = seq
            self.pending_batches = self._last_seq - seq
            self.size_bytes += len(frame)
            if (self._applied_seq == self._last_seq
                    and self.size_bytes >= _COMPACT_BYTES):
                self._compact_locked()

    def _compact_locked(self) -> None:
        # dukecheck: holds self._lock
        if self._pins > 0:
            return  # a migration slice is walking the file; keep frames
        os.ftruncate(self._fd, 0)
        self.size_bytes = 0
        self.pending_batches = 0

    def compact(self) -> None:
        """Truncate to empty iff every journaled batch has been applied
        (recovery's epilogue and the graceful-shutdown path — a drained
        shutdown leaves an empty journal)."""
        with self._lock:
            if self._applied_seq == self._last_seq:
                self._compact_locked()

    def close(self) -> None:
        with self._lock:
            if self._fd < 0:
                return
            if self._applied_seq == self._last_seq:
                self._compact_locked()
            os.close(self._fd)
            self._fd = -1
