"""In-memory link database with since-feed and idempotent assert.

Parity target: SinceAwareInMemoryLinkDatabase.java:10-42 — re-asserting an
identical link (same status/kind, |confidence delta| < 1e-6) must NOT bump
the timestamp, so pollers don't see spurious changes.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .base import Link, LinkDatabase, is_same_assertion


class InMemoryLinkDatabase(LinkDatabase):
    def __init__(self):
        self._links: Dict[Tuple[str, str], Link] = {}

    def assert_link(self, link: Link) -> None:
        old = self._links.get(link.key())
        if old is not None and is_same_assertion(old, link):
            return
        self._links[link.key()] = link

    def get_all_links_for(self, record_id: str) -> List[Link]:
        return [
            l for l in self._links.values()
            if l.id1 == record_id or l.id2 == record_id
        ]

    def get_links_for_ids(self, record_ids) -> List[Link]:
        ids = set(record_ids)
        return [
            l for l in self._links.values()
            if l.id1 in ids or l.id2 in ids
        ]

    def get_all_links(self) -> List[Link]:
        return list(self._links.values())

    def get_changes_since(self, since: int) -> List[Link]:
        # linear timestamp scan (SinceAwareInMemoryLinkDatabase.java:33-41),
        # strictly-greater-than semantics
        return sorted(
            (l for l in self._links.values() if l.timestamp > since),
            key=lambda l: (l.timestamp, l.id1, l.id2),
        )
