"""In-memory link database with since-feed and idempotent assert.

Parity target: SinceAwareInMemoryLinkDatabase.java:10-42 — re-asserting an
identical link (same status/kind, |confidence delta| < 1e-6) must NOT bump
the timestamp, so pollers don't see spurious changes.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from .base import Link, LinkDatabase, is_same_assertion


class InMemoryLinkDatabase(LinkDatabase):
    def __init__(self):
        self._links: Dict[Tuple[str, str], Link] = {}
        # timestamp-ordered view, built lazily and invalidated on writes so
        # paging a large feed costs one sort total, not one per page
        self._sorted: Optional[List[Link]] = None

    def assert_link(self, link: Link) -> None:
        old = self._links.get(link.key())
        if old is link:
            # caller mutated the stored object in place (retract() then
            # re-assert, the workload's deletion flow) — the ordered view
            # is stale even though the dict entry is unchanged
            self._sorted = None
            return
        if old is not None and is_same_assertion(old, link):
            return
        self._links[link.key()] = link
        self._sorted = None

    def get_all_links_for(self, record_id: str) -> List[Link]:
        return [
            l for l in self._links.values()
            if l.id1 == record_id or l.id2 == record_id
        ]

    def get_links_for_ids(self, record_ids) -> List[Link]:
        ids = set(record_ids)
        return [
            l for l in self._links.values()
            if l.id1 in ids or l.id2 in ids
        ]

    def get_all_links(self) -> List[Link]:
        return list(self._links.values())

    def _ordered(self) -> List[Link]:
        if self._sorted is None:
            self._sorted = sorted(
                self._links.values(),
                key=lambda l: (l.timestamp, l.id1, l.id2),
            )
        return self._sorted

    def get_changes_since(self, since: int) -> List[Link]:
        # timestamp order (SinceAwareInMemoryLinkDatabase.java:33-41),
        # strictly-greater-than semantics
        ordered = self._ordered()
        start = bisect.bisect_right(ordered, since, key=lambda l: l.timestamp)
        return ordered[start:]

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        ordered = self._ordered()
        start = bisect.bisect_right(ordered, since, key=lambda l: l.timestamp)
        if limit <= 0 or start + limit >= len(ordered):
            return ordered[start:]
        cut = start + limit
        last_ts = ordered[cut - 1].timestamp
        while cut < len(ordered) and ordered[cut].timestamp == last_ts:
            cut += 1
        return ordered[start:cut]
