"""In-memory link database with since-feed and idempotent assert.

Parity target: SinceAwareInMemoryLinkDatabase.java:10-42 — re-asserting an
identical link (same status/kind, |confidence delta| < 1e-6) must NOT bump
the timestamp, so pollers don't see spurious changes.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from ..telemetry import tracing
from .base import Link, LinkDatabase, is_same_assertion


class InMemoryLinkDatabase(LinkDatabase):
    _SORT_KEY = staticmethod(lambda l: (l.timestamp, l.id1, l.id2))

    def __init__(self):
        self._links: Dict[Tuple[str, str], Link] = {}
        # timestamp-ordered view, built lazily and maintained INCREMENTALLY
        # on writes: new links carry a fresh (strictly monotonic) timestamp
        # so they append at the tail, replaced/mutated links are removed
        # first.  Keeping the view live matters for the streaming feed —
        # invalidating on every write would make each page of a paged
        # GET ?since= re-sort the whole set under the workload lock
        # whenever ingest interleaves with paging.
        self._sorted: Optional[List[Link]] = None

    def _append_sorted(self, link: Link) -> None:
        s = self._sorted
        key = self._SORT_KEY
        if s and key(s[-1]) > key(link):
            # out-of-order write (explicit historical timestamp, e.g.
            # imported data): insert at the right position
            bisect.insort(s, link, key=key)
        else:
            s.append(link)

    def _remove_sorted(self, old: Link) -> None:
        s = self._sorted
        # fast path: locate by sort key (valid while the object is
        # unmutated) and confirm identity
        i = bisect.bisect_left(s, self._SORT_KEY(old), key=self._SORT_KEY)
        if i < len(s) and s[i] is old:
            del s[i]
            return
        # mutated in place (retract() bumped the timestamp before this
        # call): C-speed identity scan — Link defines no __eq__
        try:
            s.remove(old)
        except ValueError:
            self._sorted = None  # unseen object; rebuild lazily

    def assert_link(self, link: Link) -> None:
        old = self._links.get(link.key())
        if old is link:
            # caller mutated the stored object in place (retract() then
            # re-assert, the workload's deletion flow): re-position it
            if self._sorted is not None:
                self._remove_sorted(link)
                if self._sorted is not None:
                    self._append_sorted(link)
            return
        if old is not None and is_same_assertion(old, link):
            return
        self._links[link.key()] = link
        if self._sorted is not None:
            if old is not None:
                self._remove_sorted(old)
            if self._sorted is not None:
                self._append_sorted(link)

    def assert_links(self, links: List[Link]) -> None:
        # per-link assert is already O(1) in memory; the override only
        # adds the per-batch trace span the sqlite backend gets, so the
        # persist phase is attributable on either backend
        with tracing.span("links:assert_batch",
                          {"backend": "in-memory", "links": len(links)}):
            for link in links:
                self.assert_link(link)

    def get_all_links_for(self, record_id: str) -> List[Link]:
        # COPIES, not the stored objects (matching the sqlite backend's
        # fresh rows): callers retract-then-reassert these, and an
        # in-place mutation of a stored link would invalidate its sort key
        # before assert_link sees it — degrading every retraction to an
        # O(n) identity scan of the ordered view
        return [
            l.copy() for l in self._links.values()
            if l.id1 == record_id or l.id2 == record_id
        ]

    def get_links_for_ids(self, record_ids) -> List[Link]:
        ids = set(record_ids)
        # per-batch query (the one-to-one flush): coarse enough to span
        with tracing.span("links:links_for_ids",
                          {"backend": "in-memory", "ids": len(ids)}):
            return [
                l.copy() for l in self._links.values()
                if l.id1 in ids or l.id2 in ids
            ]

    def get_all_links(self) -> List[Link]:
        return list(self._links.values())

    def count(self) -> int:
        # lock-free O(1): len() of a dict is safe against concurrent
        # writers under the GIL, so /stats never waits on ingest
        return len(self._links)

    def _ordered(self) -> List[Link]:
        if self._sorted is None:
            self._sorted = sorted(
                self._links.values(),
                key=lambda l: (l.timestamp, l.id1, l.id2),
            )
        return self._sorted

    def get_changes_since(self, since: int) -> List[Link]:
        # timestamp order (SinceAwareInMemoryLinkDatabase.java:33-41),
        # strictly-greater-than semantics
        ordered = self._ordered()
        start = bisect.bisect_right(ordered, since, key=lambda l: l.timestamp)
        return ordered[start:]

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        with tracing.span("links:changes_page",
                          {"backend": "in-memory", "since": since}):
            ordered = self._ordered()
            start = bisect.bisect_right(
                ordered, since, key=lambda l: l.timestamp)
            if limit <= 0 or start + limit >= len(ordered):
                return ordered[start:]
            cut = start + limit
            last_ts = ordered[cut - 1].timestamp
            while cut < len(ordered) and ordered[cut].timestamp == last_ts:
                cut += 1
            return ordered[start:cut]
