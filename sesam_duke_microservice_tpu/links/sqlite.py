"""Durable link database on SQLite.

The durable backend behind ``link-database-type="h2"`` (the reference embeds
H2 via Duke's JDBCLinkDatabase, App.java:577-604; SQLite is the natural
stdlib equivalent).  Same semantics as the in-memory flavor: idempotent
assert, strictly-greater-than since feed, retraction as a status update.
Safe for multi-threaded use (one connection per thread).
"""

from __future__ import annotations

import sqlite3
from typing import List

from ..telemetry import tracing
from ..utils.sqlite import SqliteConnectionPool
from .base import Link, LinkDatabase, LinkKind, LinkStatus, is_same_assertion

_SCHEMA = """
CREATE TABLE IF NOT EXISTS links (
    id1 TEXT NOT NULL,
    id2 TEXT NOT NULL,
    status TEXT NOT NULL,
    kind TEXT NOT NULL,
    confidence REAL NOT NULL,
    timestamp INTEGER NOT NULL,
    PRIMARY KEY (id1, id2)
);
CREATE INDEX IF NOT EXISTS links_ts ON links (timestamp);
CREATE INDEX IF NOT EXISTS links_id2 ON links (id2);
"""


class SqliteLinkDatabase(LinkDatabase):
    def __init__(self, path: str):
        self.path = path
        self._pool = SqliteConnectionPool(path)
        with self._conn() as conn:
            conn.executescript(_SCHEMA)

    def _conn(self) -> sqlite3.Connection:
        return self._pool.conn()

    @staticmethod
    def _row_to_link(row) -> Link:
        return Link(row[0], row[1], LinkStatus(row[2]), LinkKind(row[3]),
                    row[4], row[5])

    def assert_link(self, link: Link) -> None:
        conn = self._conn()
        cur = conn.execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE id1=? AND id2=?",
            (link.id1, link.id2),
        )
        row = cur.fetchone()
        if row is not None and is_same_assertion(self._row_to_link(row), link):
            return
        conn.execute(
            "INSERT INTO links (id1, id2, status, kind, confidence, timestamp) "
            "VALUES (?,?,?,?,?,?) ON CONFLICT(id1, id2) DO UPDATE SET "
            "status=excluded.status, kind=excluded.kind, "
            "confidence=excluded.confidence, timestamp=excluded.timestamp",
            (link.id1, link.id2, link.status.value, link.kind.value,
             link.confidence, link.timestamp),
        )
        conn.commit()

    def get_all_links_for(self, record_id: str) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE id1=? OR id2=?",
            (record_id, record_id),
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def get_links_for_ids(self, record_ids) -> List[Link]:
        ids = sorted(set(record_ids))
        if not ids:
            return []
        out: List[Link] = []
        conn = self._conn()
        # per-batch query (the one-to-one flush) — coarse enough to span
        # without crowding the trace scratch (per-link ops are not spanned)
        with tracing.span("links:links_for_ids",
                          {"backend": "sqlite", "ids": len(ids)}):
            # SQLite caps host parameters (999 on older builds); chunk the IN
            for start in range(0, len(ids), 450):
                chunk = ids[start:start + 450]
                marks = ",".join("?" * len(chunk))
                cur = conn.execute(
                    "SELECT id1, id2, status, kind, confidence, timestamp "
                    f"FROM links WHERE id1 IN ({marks}) OR id2 IN ({marks})",
                    chunk + chunk,
                )
                out.extend(self._row_to_link(r) for r in cur.fetchall())
            if len(ids) > 450:  # chunks can double-report a joining link
                out = list({l.key(): l for l in out}.values())
        return out

    def get_all_links(self) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links"
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def count(self) -> int:
        cur = self._conn().execute("SELECT COUNT(*) FROM links")
        return int(cur.fetchone()[0])

    def get_changes_since(self, since: int) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE timestamp > ? ORDER BY timestamp, id1, id2",
            (since,),
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        if limit <= 0:
            return self.get_changes_since(since)
        conn = self._conn()
        with tracing.span("links:changes_page",
                          {"backend": "sqlite", "since": since}):
            return self._changes_page(conn, since, limit)

    def _changes_page(self, conn, since: int, limit: int) -> List[Link]:
        cur = conn.execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE timestamp > ? ORDER BY timestamp, id1, id2 LIMIT ?",
            (since, limit),
        )
        page = [self._row_to_link(r) for r in cur.fetchall()]
        if len(page) == limit:
            # extend over timestamp ties at the page edge (see base): the
            # next page's strictly-greater cursor must not skip tied rows
            last = page[-1]
            cur = conn.execute(
                "SELECT id1, id2, status, kind, confidence, timestamp "
                "FROM links WHERE timestamp = ? ORDER BY id1, id2",
                (last.timestamp,),
            )
            for r in cur.fetchall():
                if (r[0], r[1]) > (last.id1, last.id2):
                    page.append(self._row_to_link(r))
        return page

    def close(self) -> None:
        self._pool.close()
