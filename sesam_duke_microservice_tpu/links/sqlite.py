"""Durable link database on SQLite.

The durable backend behind ``link-database-type="h2"`` (the reference embeds
H2 via Duke's JDBCLinkDatabase, App.java:577-604; SQLite is the natural
stdlib equivalent).  Same semantics as the in-memory flavor: idempotent
assert, strictly-greater-than since feed, retraction as a status update.
Safe for multi-threaded use (one connection per thread).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

from ..telemetry import tracing
from ..utils.sqlite import SqliteConnectionPool
from .base import Link, LinkDatabase, LinkKind, LinkStatus, is_same_assertion

_SCHEMA = """
CREATE TABLE IF NOT EXISTS links (
    id1 TEXT NOT NULL,
    id2 TEXT NOT NULL,
    status TEXT NOT NULL,
    kind TEXT NOT NULL,
    confidence REAL NOT NULL,
    timestamp INTEGER NOT NULL,
    PRIMARY KEY (id1, id2)
);
CREATE INDEX IF NOT EXISTS links_ts ON links (timestamp);
CREATE INDEX IF NOT EXISTS links_id2 ON links (id2);
"""


_UPSERT = (
    "INSERT INTO links (id1, id2, status, kind, confidence, timestamp) "
    "VALUES (?,?,?,?,?,?) ON CONFLICT(id1, id2) DO UPDATE SET "
    "status=excluded.status, kind=excluded.kind, "
    "confidence=excluded.confidence, timestamp=excluded.timestamp"
)


def _upsert_params(link: Link) -> Tuple:
    return (link.id1, link.id2, link.status.value, link.kind.value,
            link.confidence, link.timestamp)


class SqliteLinkDatabase(LinkDatabase):
    def __init__(self, path: str):
        self.path = path
        self._pool = SqliteConnectionPool(path)
        # incremental row counter: /metrics scrapes call count() per
        # workload, and a full-table COUNT(*) is O(rows) against the
        # millions-of-links target.  Initialized lazily from one COUNT(*)
        # and maintained on every write (each write path knows whether the
        # key existed).  Single-process assumption only — the same one the
        # per-workload data folder has always made.
        self._count_lock = threading.Lock()
        self._count: Optional[int] = None
        with self._conn() as conn:
            conn.executescript(_SCHEMA)

    def _conn(self) -> sqlite3.Connection:
        return self._pool.conn()

    @staticmethod
    def _row_to_link(row) -> Link:
        return Link(row[0], row[1], LinkStatus(row[2]), LinkKind(row[3]),
                    row[4], row[5])

    def assert_link(self, link: Link) -> None:
        conn = self._conn()
        cur = conn.execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE id1=? AND id2=?",
            (link.id1, link.id2),
        )
        row = cur.fetchone()
        if row is not None and is_same_assertion(self._row_to_link(row), link):
            return
        conn.execute(_UPSERT, _upsert_params(link))
        conn.commit()
        if row is None:
            self._count_add(1)

    def assert_links(self, links: List[Link]) -> None:
        """One transaction for a whole batch of asserts.

        Semantics match sequential ``assert_link`` calls exactly: the
        batch's keys are prefetched in one chunked query, identical
        re-asserts (vs the stored row OR an earlier link in the batch) are
        skipped without a timestamp-visible write, and only each key's
        final effective state is upserted — the same table contents a
        per-link loop would leave, at one ``executemany`` + one commit.
        """
        if not links:
            return
        conn = self._conn()
        with tracing.span("links:assert_batch",
                          {"backend": "sqlite", "links": len(links)}):
            keys = sorted({link.key() for link in links})
            existing: Dict[Tuple[str, str], Link] = {}
            for start in range(0, len(keys), 225):  # 2 params per key
                chunk = keys[start:start + 225]
                clause = " OR ".join("(id1=? AND id2=?)" for _ in chunk)
                cur = conn.execute(
                    "SELECT id1, id2, status, kind, confidence, timestamp "
                    f"FROM links WHERE {clause}",
                    [v for key in chunk for v in key],
                )
                for row in cur.fetchall():
                    existing[(row[0], row[1])] = self._row_to_link(row)
            effective = dict(existing)
            to_write: Dict[Tuple[str, str], Link] = {}
            for link in links:
                current = effective.get(link.key())
                if current is not None and is_same_assertion(current, link):
                    continue
                effective[link.key()] = link
                to_write[link.key()] = link
            if not to_write:
                return
            inserted = sum(1 for key in to_write if key not in existing)
            conn.executemany(
                _UPSERT,
                [_upsert_params(link) for link in to_write.values()],
            )
            conn.commit()
            self._count_add(inserted)

    def get_all_links_for(self, record_id: str) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE id1=? OR id2=?",
            (record_id, record_id),
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def get_links_for_ids(self, record_ids) -> List[Link]:
        ids = sorted(set(record_ids))
        if not ids:
            return []
        out: List[Link] = []
        conn = self._conn()
        # per-batch query (the one-to-one flush) — coarse enough to span
        # without crowding the trace scratch (per-link ops are not spanned)
        with tracing.span("links:links_for_ids",
                          {"backend": "sqlite", "ids": len(ids)}):
            # SQLite caps host parameters (999 on older builds); chunk the IN
            for start in range(0, len(ids), 450):
                chunk = ids[start:start + 450]
                marks = ",".join("?" * len(chunk))
                cur = conn.execute(
                    "SELECT id1, id2, status, kind, confidence, timestamp "
                    f"FROM links WHERE id1 IN ({marks}) OR id2 IN ({marks})",
                    chunk + chunk,
                )
                out.extend(self._row_to_link(r) for r in cur.fetchall())
            if len(ids) > 450:  # chunks can double-report a joining link
                out = list({l.key(): l for l in out}.values())
        return out

    def get_all_links(self) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links"
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def _count_add(self, inserted: int) -> None:
        # short critical section AFTER the commit: the lock never spans a
        # sqlite transaction, so a concurrent count() cannot block on an
        # in-flight flush.  (A count() initialization racing the window
        # between a commit and this increment can over-count that batch
        # once — an accepted one-off skew on a monitoring gauge.)
        if inserted:
            with self._count_lock:
                if self._count is not None:
                    self._count += inserted

    def count(self) -> int:
        # O(1) after the first call: the cached counter is maintained by
        # every write path (ROADMAP open item — COUNT(*) per /metrics
        # scrape was O(rows) against the millions-of-links target)
        value = self._count
        if value is not None:
            return value
        with self._count_lock:
            if self._count is None:
                cur = self._conn().execute("SELECT COUNT(*) FROM links")
                self._count = int(cur.fetchone()[0])
            return self._count

    def get_changes_since(self, since: int) -> List[Link]:
        cur = self._conn().execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE timestamp > ? ORDER BY timestamp, id1, id2",
            (since,),
        )
        return [self._row_to_link(r) for r in cur.fetchall()]

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        if limit <= 0:
            return self.get_changes_since(since)
        conn = self._conn()
        with tracing.span("links:changes_page",
                          {"backend": "sqlite", "since": since}):
            return self._changes_page(conn, since, limit)

    def _changes_page(self, conn, since: int, limit: int) -> List[Link]:
        cur = conn.execute(
            "SELECT id1, id2, status, kind, confidence, timestamp FROM links "
            "WHERE timestamp > ? ORDER BY timestamp, id1, id2 LIMIT ?",
            (since, limit),
        )
        page = [self._row_to_link(r) for r in cur.fetchall()]
        if len(page) == limit:
            # extend over timestamp ties at the page edge (see base): the
            # next page's strictly-greater cursor must not skip tied rows
            last = page[-1]
            cur = conn.execute(
                "SELECT id1, id2, status, kind, confidence, timestamp "
                "FROM links WHERE timestamp = ? ORDER BY id1, id2",
                (last.timestamp,),
            )
            for r in cur.fetchall():
                if (r[0], r[1]) > (last.id1, last.id2):
                    page.append(self._row_to_link(r))
        return page

    def close(self) -> None:
        self._pool.close()
