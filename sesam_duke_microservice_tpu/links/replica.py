"""Replicated link serving: the leader-side publisher and the
follower-side replica link database (ISSUE 8 tentpole).

The reference design funnels every ``?since=`` poll through the one
process that owns the link DB (App.java:742,843); our multi-host mode
inherited that — process 0 served all reads under the workload locks.
This module turns the ordered, committed link batches the leader already
produces (``links/write_behind.py`` seals exactly these batches; the
one-to-one flush's retractions and conflict rewrites ride the same
arrival order) into first-class dispatch ops so every follower maintains
a local replica and serves feed polls itself:

  * ``PublishingLinkDatabase`` — leader-side wrapper installed by the
    dispatcher around each workload's link database.  Writes pass
    through untouched; ``commit()`` seals the arrival-ordered batch,
    assigns the next monotonic sequence number, and hands the encoded
    rows to a publish callback (``Dispatcher.broadcast`` in production).
    Rows are encoded *at assert time* because callers mutate Link
    objects in place (retract-then-reassert).
  * ``ReplicaLinkDatabase`` — follower-side replica: an in-memory link
    DB that applies published batches under a monotonic applied-seq
    watermark.  Duplicate batches (fault-injected dup delivery, leader
    resend) are dropped by the watermark; a sequence *gap* raises —
    a replica that missed a batch must resync, never silently serve a
    hole.  Leader timestamps are preserved verbatim, so a replica feed
    page is bit-identical to the leader's at the same watermark.

``feed_row``/``links_feed_page`` are THE feed-row materialization —
``engine.workload.Workload`` and the follower read plane both call them,
so leader and replica feeds cannot drift by construction.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

from ..core.records import (
    DATASET_ID_PROPERTY_NAME,
    ORIGINAL_ENTITY_ID_PROPERTY_NAME,
)
from .base import Link, LinkDatabase, LinkKind, LinkStatus
from .memory import InMemoryLinkDatabase

# one link on the wire: plain tuple, no pickle-by-reference surprises
LinkRow = Tuple[str, str, str, str, float, int]


def encode_link(link: Link) -> LinkRow:
    return (link.id1, link.id2, link.status.value, link.kind.value,
            link.confidence, link.timestamp)


def decode_link(row: Sequence) -> Link:
    id1, id2, status, kind, confidence, timestamp = row
    return Link(id1, id2, LinkStatus(status), LinkKind(kind), confidence,
                timestamp=timestamp)


def rows_checksum(rows: Sequence[Sequence]) -> int:
    """CRC32 chained over the canonical JSON of encoded link rows — the
    integrity stamp on shipped link state (the range-migration snapshot,
    ISSUE 14; same stance as the corpus snapshot's ``__checksum``: the
    transport may be fine while the payload is not).  Order-sensitive by
    design: the rows travel in arrival order and must land that way."""
    import json
    import zlib

    crc = 0
    for row in rows:
        crc = zlib.crc32(
            json.dumps(list(row), separators=(",", ":"),
                       ensure_ascii=True).encode("utf-8"), crc)
    return crc


class ReplicaGap(RuntimeError):
    """The replica missed at least one published batch: its feed would
    silently serve a hole, so it must resync (re-bootstrap) instead."""


class PublishingLinkDatabase(LinkDatabase):
    """Leader-side pass-through wrapper that publishes committed batches.

    Installed by ``Dispatcher._tag_workloads`` around the workload's link
    database (write-behind wrapper or bare backend alike), so EVERY link
    write — scoring matches, one-to-one retractions/rewrites, delete
    retractions — is captured in arrival order.  ``commit()`` seals the
    captured rows as one batch with the next sequence number and invokes
    ``publish(seq, rows)``; an empty buffer publishes nothing.

    The publish happens after the inner commit returns, i.e. after the
    write-behind wrapper *enqueued* (not necessarily flushed) the batch:
    a leader crash between flush and publish can leave replicas with
    rows the leader's disk never saw — the failover direction that
    loses nothing (the promoted replica is ahead, never behind).
    """

    def __init__(self, inner: LinkDatabase,
                 publish: Callable[[int, List[LinkRow]], None],
                 seq: int = 0):
        self.inner = inner
        self._publish = publish
        self._pending: List[LinkRow] = []  # single-writer: ingest path under the workload lock
        self.seq = seq

    # -- writes (captured in arrival order) ----------------------------------

    def assert_link(self, link: Link) -> None:
        self.inner.assert_link(link)
        self._pending.append(encode_link(link))

    def assert_links(self, links: List[Link]) -> None:
        self.inner.assert_links(links)
        self._pending.extend(encode_link(l) for l in links)

    def commit(self) -> None:
        self.inner.commit()
        if self._pending:
            # seq advances and the buffer clears only AFTER the publish
            # returns: a publish that raises (frontend-desync latch, an
            # injected leader crash the process survives) leaves the
            # batch pending under the SAME seq, so the next successful
            # commit re-publishes it (merged with newer writes, arrival
            # order intact) instead of leaving a silent hole every
            # replica would trip over as a ReplicaGap.
            self._publish(self.seq + 1, self._pending)
            self.seq += 1
            self._pending = []

    # -- reads / lifecycle (delegate) ----------------------------------------

    def get_all_links_for(self, record_id: str) -> List[Link]:
        return self.inner.get_all_links_for(record_id)

    def get_links_for_ids(self, record_ids) -> List[Link]:
        return self.inner.get_links_for_ids(record_ids)

    def get_all_links(self) -> List[Link]:
        return self.inner.get_all_links()

    def count(self) -> int:
        return self.inner.count()

    def get_changes_since(self, since: int) -> List[Link]:
        return self.inner.get_changes_since(since)

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        return self.inner.get_changes_page(since, limit)

    def drain(self) -> None:
        self.inner.drain()

    @property
    def flush_error(self) -> Optional[BaseException]:
        return getattr(self.inner, "flush_error", None)

    @property
    def recovering(self) -> bool:
        """See through to the wrapped write-behind database's overlapped
        startup replay (ISSUE 15): without this, a multi-host leader's
        HTTP write fence probed the publisher, always read False, and a
        scoring POST fell through to BLOCK inside the inner fence for
        the whole replay window instead of answering the fast 503."""
        return getattr(self.inner, "recovering", False)

    @property
    def journal(self):
        """The wrapped write-behind database's durable journal, or None
        — surfaced so the /metrics journal gauges see through this
        wrapper on dispatcher-tagged workloads."""
        return getattr(self.inner, "journal", None)

    def close(self) -> None:
        self.inner.close()


class ReplicaLinkDatabase(InMemoryLinkDatabase):
    """Follower-side replica with a monotonic applied-op watermark.

    ``apply_ops`` is idempotent under duplicate delivery (seq <=
    watermark drops) and loud under loss (gap raises ``ReplicaGap``).
    ``note_head`` tracks the highest sequence number *announced* (op
    received, not yet applied) so ``lag_ops`` measures real replication
    lag for the ``X-Replica-Lag`` header and ``duke_replica_lag_ops``.

    All entry points take ``self.lock`` — the replica is written by the
    follower's replay thread and read concurrently by the follower HTTP
    read plane (no leader lock is ever involved, which is the point).
    After promotion the same object serves as the workload's link
    database; the lock then simply guards listener writes against any
    still-draining replica reads.
    """

    def __init__(self, seq: int = 0):
        super().__init__()
        self.lock = threading.RLock()
        self.applied_seq = seq  # guarded by: self.lock [writes]
        self.head_seq = seq  # guarded by: self.lock [writes]

    def load_snapshot(self, rows: Sequence[LinkRow], seq: int) -> None:
        """Adopt the leader's bootstrap link state at watermark ``seq``."""
        with self.lock:
            for row in rows:
                super().assert_link(decode_link(row))
            self.applied_seq = seq
            self.head_seq = max(self.head_seq, seq)

    def note_head(self, seq: int) -> None:
        with self.lock:
            if seq > self.head_seq:
                self.head_seq = seq

    def apply_ops(self, seq: int, rows: Sequence[LinkRow]) -> bool:
        """Fold one published batch; returns False for a duplicate."""
        with self.lock:
            if seq > self.head_seq:
                self.head_seq = seq
            if seq <= self.applied_seq:
                return False  # duplicate delivery: already folded
            if seq != self.applied_seq + 1:
                raise ReplicaGap(
                    f"link-stream gap: batch {seq} arrived at watermark "
                    f"{self.applied_seq} (missed "
                    f"{seq - self.applied_seq - 1} batch(es)); this "
                    "replica must resync"
                )
            for row in rows:
                super().assert_link(decode_link(row))
            self.applied_seq = seq
            return True

    def lag_ops(self) -> int:
        with self.lock:
            return self.head_seq - self.applied_seq

    # -- locked LinkDatabase surface -----------------------------------------
    # (the in-memory base is written for single-writer workload-locked use;
    # here the replay thread and the read plane interleave freely)

    def assert_link(self, link: Link) -> None:
        with self.lock:
            super().assert_link(link)

    def assert_links(self, links: List[Link]) -> None:
        with self.lock:
            super().assert_links(links)

    def get_all_links_for(self, record_id: str) -> List[Link]:
        with self.lock:
            return super().get_all_links_for(record_id)

    def get_links_for_ids(self, record_ids) -> List[Link]:
        with self.lock:
            return super().get_links_for_ids(record_ids)

    def get_all_links(self) -> List[Link]:
        with self.lock:
            return super().get_all_links()

    def get_changes_since(self, since: int) -> List[Link]:
        with self.lock:
            return super().get_changes_since(since)

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        with self.lock:
            return super().get_changes_page(since, limit)


# -- shared feed materialization ---------------------------------------------


def feed_row(link: Link, find_record_by_id) -> dict:
    """One ``?since=`` feed row (wire format per App.java:744-770).

    THE single materialization: the leader's ``Workload._link_row`` and
    the follower read plane both resolve through this, so replica feeds
    are bit-identical to the leader's at the same watermark."""
    r1 = find_record_by_id(link.id1)
    r2 = find_record_by_id(link.id2)
    return {
        "_id": f"{link.id1}_{link.id2}".replace(":", "_"),
        "_updated": link.timestamp,
        "_deleted": link.status == LinkStatus.RETRACTED,
        "entity1": r1.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME) if r1 else None,
        "entity2": r2.get_value(ORIGINAL_ENTITY_ID_PROPERTY_NAME) if r2 else None,
        "dataset1": r1.get_value(DATASET_ID_PROPERTY_NAME) if r1 else None,
        "dataset2": r2.get_value(DATASET_ID_PROPERTY_NAME) if r2 else None,
        "confidence": link.confidence,
    }


def links_feed_page(link_db: LinkDatabase, index, since: int, limit: int):
    """One bounded feed page: (rows, next_cursor) — see
    ``Workload.links_page`` for the paging contract.  Lazy record
    mirrors resolve link endpoints through one batched prefetch."""
    links = link_db.get_changes_page(since, limit)
    if not links:
        return [], since
    prefetch = getattr(getattr(index, "records", None), "prefetch", None)
    if prefetch is not None:
        ids = {l.id1 for l in links} | {l.id2 for l in links}
        prefetch(ids)
    return ([feed_row(l, index.find_record_by_id) for l in links],
            links[-1].timestamp)
