"""Write-behind persistence: generic buffer + the link-database wrapper.

The persist phase used to flush each batch's link upserts synchronously
inside ``batch_done`` — serial with the next microbatch's encode phase.
``WriteBehindLinkDatabase`` buffers writes in arrival order and flushes
them on a single background thread (one ``assert_links`` transaction +
``commit`` per batch), so the durable flush overlaps the next
microbatch's encode/device work instead of extending the persist phase.

The buffering/flusher/latch core is ``WriteBehindBuffer`` — extracted so
the decision audit log (telemetry.decisions.AuditLog, ISSUE 5) rides the
SAME machinery instead of growing a second background-flush
implementation with subtly different drain/latch rules.

Consistency contract (the link wrapper):

  * **Ordering** — writes apply in arrival order; ``commit()`` seals the
    current buffer as one batch and enqueues it (non-blocking).
  * **Drain barrier** — every row-returning read (``/datasets`` feed
    pages, the one-to-one flush's batched link fetch, delete-retraction
    lookups) drains buffered and in-flight writes first, so a reader can
    never observe a torn batch.  ``close()`` and the workload's
    corpus-snapshot save drain too.  ``count()`` alone is non-draining:
    it feeds monitoring gauges, which must not block on flush latency.
  * **Failure** — a background flush error latches the wrapper: the batch
    that failed was ONE transaction (all-or-nothing on the sqlite
    backend), and every subsequent write/commit/drain raises the latched
    error so ingest cannot silently run ahead of a dead link store.
    Recovery is a workload reload/restart, same as any persistent-store
    failure.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Callable, List, Optional, Sequence

from ..utils import faults
from .base import Link, LinkDatabase

logger = logging.getLogger("links-write-behind")


class WriteBehindBuffer:
    """Generic arrival-order write-behind core.

    Items accumulate in an open buffer; ``commit()`` seals the buffer as
    one batch and enqueues it for the background flusher, which hands
    each batch to ``flush`` (one call per batch — the transaction
    boundary).  ``drain()`` is the read barrier; a flush failure latches
    the buffer (every later ``add``/``commit``/``drain`` raises), unless
    constructed with ``drop_on_overflow`` AND the embedder opts to treat
    the latch as advisory by catching the error.

    ``max_pending`` bounds the sealed-batch queue.  Past it, ``commit()``
    either blocks (backpressure — the link-database stance: a slow disk
    must throttle ingest, not grow memory) or, with
    ``drop_on_overflow=True``, discards the oldest pending batch and
    counts it in ``dropped`` (the audit-log stance: observability output
    must never block scoring).
    """

    def __init__(self, flush: Callable[[List], None], *,
                 max_pending: int = 4, drop_on_overflow: bool = False,
                 name: str = "write-behind"):
        self._flush = flush
        self._max_pending = max(1, max_pending)
        self._drop_on_overflow = drop_on_overflow
        self._name = name
        self._cv = threading.Condition()
        self._buf: List = []  # guarded by: self._cv
        self._queue: deque = deque()  # guarded by: self._cv
        self._inflight = False  # guarded by: self._cv
        self._error: Optional[BaseException] = None  # guarded by: self._cv [writes]
        self._closed = False  # guarded by: self._cv
        self._thread: Optional[threading.Thread] = None  # guarded by: self._cv
        # batches discarded by the overflow policy; read lock-free by stats
        self.dropped = 0  # guarded by: self._cv [writes]

    # -- worker --------------------------------------------------------------

    def _ensure_thread(self) -> None:
        # dukecheck: holds self._cv
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue:
                    return  # closed and drained
                batch = self._queue.popleft()
                self._inflight = True
            try:
                self._flush(batch)
            except BaseException as e:  # latch: readers/writers must see it
                logger.exception("%s flush failed", self._name)
                with self._cv:
                    self._error = e
                    self._inflight = False
                    self._queue.clear()
                    self._cv.notify_all()
                return
            with self._cv:
                self._inflight = False
                self._cv.notify_all()

    def _raise_latched(self) -> None:
        # dukecheck: holds self._cv
        if self._error is not None:
            raise RuntimeError(
                f"{self._name} flush failed; the backing store is stale"
            ) from self._error

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    # -- writes (buffered, arrival order) ------------------------------------

    def add(self, item) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.append(item)

    def add_many(self, items: Sequence) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.extend(items)

    def commit(self) -> None:
        """Seal the buffered writes as one batch and enqueue the flush;
        returns immediately unless the flusher is ``max_pending`` batches
        behind (then it blocks — or drops the oldest pending batch under
        ``drop_on_overflow``)."""
        with self._cv:
            self._raise_latched()
            if not self._buf:
                return
            while len(self._queue) >= self._max_pending:
                if self._drop_on_overflow:
                    self._queue.popleft()
                    self.dropped += 1
                    continue
                self._cv.wait()
                self._raise_latched()
            batch, self._buf = self._buf, []
            self._queue.append(batch)
            self._ensure_thread()
            self._cv.notify_all()

    def drain(self) -> None:
        """Block until every buffered and queued write is durably applied
        (the read barrier; re-raises a latched flush failure)."""
        self.commit()
        with self._cv:
            while (self._queue or self._inflight) and self._error is None:
                self._cv.wait()
            self._raise_latched()

    def close(self) -> None:
        """Drain (best-effort past a latched failure) and stop the
        flusher thread.  Does NOT close whatever ``flush`` writes to —
        that remains the embedder's resource."""
        try:
            self.drain()
        except RuntimeError:
            pass  # latched failure: nothing left to save
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=10.0)


class WriteBehindLinkDatabase(LinkDatabase):
    # backpressure: at most this many sealed batches may be pending
    # behind the flusher; commit() blocks past it, so a slow disk turns
    # into ingest backpressure instead of unbounded queue growth — and
    # every drain barrier (reads, scrapes) is bounded by a handful of
    # flush transactions rather than an arbitrary backlog
    _MAX_PENDING = 4

    def __init__(self, inner: LinkDatabase):
        self.inner = inner
        self._wb = WriteBehindBuffer(
            self._flush_batch, max_pending=self._MAX_PENDING,
            name="link write-behind",
        )

    def _flush_batch(self, batch: List[Link]) -> None:
        plan = faults.active()
        if plan is not None:
            # chaos hook (DUKE_FAULTS flush_fail): a raised injection
            # latches the buffer exactly like a real disk failure
            plan.check_flush("link write-behind")
        self.inner.assert_links(batch)
        self.inner.commit()

    @property
    def flush_error(self) -> Optional[BaseException]:
        """The latched background-flush failure, or None (read lock-free
        by health probes: a dead persistence thread must be visible to
        orchestrators without waiting for a read to drain into it)."""
        return self._wb.error

    # test/introspection compatibility: the sealed-batch queue lives in
    # the shared buffer now
    @property
    def _queue(self) -> deque:
        return self._wb._queue  # dukecheck: ignore[DK202] test introspection handle; callers must hold _wb._cv to iterate

    # -- writes (buffered, arrival order) ------------------------------------

    def assert_link(self, link: Link) -> None:
        self._wb.add(link)

    def assert_links(self, links: List[Link]) -> None:
        self._wb.add_many(links)

    def commit(self) -> None:
        self._wb.commit()

    def drain(self) -> None:
        self._wb.drain()

    # -- reads (drain first) -------------------------------------------------

    def get_all_links_for(self, record_id: str) -> List[Link]:
        self.drain()
        return self.inner.get_all_links_for(record_id)

    def get_links_for_ids(self, record_ids) -> List[Link]:
        self.drain()
        return self.inner.get_links_for_ids(record_ids)

    def get_all_links(self) -> List[Link]:
        self.drain()
        return self.inner.get_all_links()

    def count(self) -> int:
        # deliberately NOT drained: count feeds /metrics and /stats
        # gauges, and a scrape must neither block on in-flight flush
        # transactions nor seal another thread's in-progress batch buffer
        # into a separate transaction.  The value trails the buffered
        # writes by at most a batch or two (exact again after any drain
        # point); every row-returning read keeps the full barrier.
        return self.inner.count()

    def get_changes_since(self, since: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_since(since)

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_page(since, limit)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._wb.close()
        self.inner.close()
