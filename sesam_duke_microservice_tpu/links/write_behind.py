"""Write-behind persistence: generic buffer + the link-database wrapper.

The persist phase used to flush each batch's link upserts synchronously
inside ``batch_done`` — serial with the next microbatch's encode phase.
``WriteBehindLinkDatabase`` buffers writes in arrival order and flushes
them on a single background thread (one ``assert_links`` transaction +
``commit`` per batch), so the durable flush overlaps the next
microbatch's encode/device work instead of extending the persist phase.

The buffering/flusher/latch core is ``WriteBehindBuffer`` — extracted so
the decision audit log (telemetry.decisions.AuditLog, ISSUE 5) rides the
SAME machinery instead of growing a second background-flush
implementation with subtly different drain/latch rules.

Consistency contract (the link wrapper):

  * **Ordering** — writes apply in arrival order; ``commit()`` seals the
    current buffer as one batch and enqueues it (non-blocking).
  * **Durability** (ISSUE 10) — with a ``LinkJournal`` attached (the
    default for sqlite-backed workloads, ``DUKE_JOURNAL``), sealing a
    batch appends it to the append-only journal — rows + monotonic batch
    seq + CRC, synced per ``DUKE_JOURNAL_SYNC`` — BEFORE ``commit()``
    returns, i.e. before the HTTP ack.  The background flusher is then a
    redo-log applier: it advances the journal's applied watermark after
    each durable store commit, and startup ``recover()`` replays any
    journaled batch a crash stranded through the idempotent
    ``assert_links`` path.  Without a journal the pre-PR loss window
    remains: an acked batch lives only in this buffer until the flush
    lands (in-memory link-DB semantics for that window).
  * **Drain barrier** — every row-returning read (``/datasets`` feed
    pages, the one-to-one flush's batched link fetch, delete-retraction
    lookups) drains buffered and in-flight writes first, so a reader can
    never observe a torn batch.  ``close()`` and the workload's
    corpus-snapshot save drain too.  ``count()`` alone is non-draining:
    it feeds monitoring gauges, which must not block on flush latency.
  * **Failure** — a background flush failure is retried per batch
    (``DUKE_FLUSH_RETRIES``, default 3, capped exponential backoff with
    full jitter) before latching the wrapper: transient disk errors heal
    in place — safe because the batch is journaled (or, journal-less,
    still held in the queue) across attempts — while a persistent error
    still latches: the batch that failed was ONE transaction
    (all-or-nothing on the sqlite backend), and every subsequent
    write/commit/drain raises the latched error so ingest cannot
    silently run ahead of a dead link store.  Recovery is a workload
    reload/restart, same as any persistent-store failure — and with the
    journal, the latched batches replay at that restart.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

from .. import telemetry
from ..telemetry.env import env_int
from ..utils import faults
from ..utils.backoff import full_jitter_delay
from .base import Link, LinkDatabase
from .journal import LinkJournal
from .replica import decode_link, encode_link

logger = logging.getLogger("links-write-behind")

# flush-retry backoff shape (satellite: transient disk errors must not
# poison the wrapper until restart) — same ladder the feed lock retries
# use; the retry COUNT is the env knob, the shape is policy
_RETRY_BASE_S = 0.05
_RETRY_CAP_S = 2.0


def _flush_retries() -> int:
    """Per-batch transient-failure retries before the latch (resolved at
    failure time so tests and operators can flip it on a live process —
    the failure path is rare, the env read is not hot)."""
    return max(0, env_int("DUKE_FLUSH_RETRIES", 3))


class WriteBehindBuffer:
    """Generic arrival-order write-behind core.

    Items accumulate in an open buffer; ``commit()`` seals the buffer as
    one batch and enqueues it for the background flusher, which hands
    each batch to ``flush`` (one call per batch — the transaction
    boundary).  ``drain()`` is the read barrier; a flush failure latches
    the buffer (every later ``add``/``commit``/``drain`` raises), unless
    constructed with ``drop_on_overflow`` AND the embedder opts to treat
    the latch as advisory by catching the error.

    ``max_pending`` bounds the sealed-batch queue.  Past it, ``commit()``
    either blocks (backpressure — the link-database stance: a slow disk
    must throttle ingest, not grow memory) or, with
    ``drop_on_overflow=True``, discards the oldest pending batch and
    counts it in ``dropped`` (the audit-log stance: observability output
    must never block scoring).
    """

    def __init__(self, flush: Callable[[List], None], *,
                 max_pending: int = 4, drop_on_overflow: bool = False,
                 name: str = "write-behind",
                 seal: Optional[Callable] = None,
                 retries: Optional[Callable[[], int]] = None):
        self._flush = flush
        # optional batch-sealing hook, called under self._cv the moment
        # commit() closes a batch and BEFORE it is enqueued — the link
        # wrapper journals the batch here, so the durability point
        # precedes both the ack and the background flush.  May transform
        # the batch (the flusher receives its return value); raising
        # restores the items to the open buffer and propagates to the
        # committer (no durability -> no ack).
        self._seal = seal
        # transient-flush-failure retries before the latch (callable so
        # the env knob is read at failure time); None = never retry
        self._retries = retries
        self._max_pending = max(1, max_pending)
        self._drop_on_overflow = drop_on_overflow
        self._name = name
        self._cv = threading.Condition()
        self._buf: List = []  # guarded by: self._cv
        self._queue: deque = deque()  # guarded by: self._cv
        self._inflight = False  # guarded by: self._cv
        self._error: Optional[BaseException] = None  # guarded by: self._cv [writes]
        self._closed = False  # guarded by: self._cv
        self._thread: Optional[threading.Thread] = None  # guarded by: self._cv
        # batches discarded by the overflow policy; read lock-free by stats
        self.dropped = 0  # guarded by: self._cv [writes]

    # -- worker --------------------------------------------------------------

    def _ensure_thread(self) -> None:
        # dukecheck: holds self._cv
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=self._name
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue:
                    return  # closed and drained
                batch = self._queue.popleft()
                self._inflight = True
            error = self._flush_with_retries(batch)
            if error is not None:  # latch: readers/writers must see it
                with self._cv:
                    self._error = error
                    self._inflight = False
                    self._queue.clear()
                    self._cv.notify_all()
                return
            with self._cv:
                self._inflight = False
                self._cv.notify_all()

    def _flush_with_retries(self, batch) -> Optional[BaseException]:
        """One batch through ``flush``, retried with capped-exponential
        full-jitter backoff for transient failures (each attempt re-runs
        the WHOLE batch — the one-transaction/idempotent-assert contract
        makes that safe).  Returns the terminal error, or None."""
        attempt = 0
        while True:
            try:
                self._flush(batch)
                return None
            except BaseException as e:
                limit = self._retries() if self._retries is not None else 0
                if attempt >= limit:
                    logger.exception(
                        "%s flush failed terminally (%d attempt(s))",
                        self._name, attempt + 1,
                    )
                    return e
                attempt += 1
                delay = full_jitter_delay(attempt, _RETRY_BASE_S,
                                          _RETRY_CAP_S)
                logger.warning(
                    "%s flush failed (attempt %d/%d; retrying in "
                    "%.3f s): %r", self._name, attempt, limit, delay, e,
                )
                time.sleep(delay)

    def _raise_latched(self) -> None:
        # dukecheck: holds self._cv
        if self._error is not None:
            raise RuntimeError(
                f"{self._name} flush failed; the backing store is stale"
            ) from self._error

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    # -- writes (buffered, arrival order) ------------------------------------

    def add(self, item) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.append(item)

    def add_many(self, items: Sequence) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.extend(items)

    def commit(self) -> None:
        """Seal the buffered writes as one batch and enqueue the flush;
        returns immediately unless the flusher is ``max_pending`` batches
        behind (then it blocks — or drops the oldest pending batch under
        ``drop_on_overflow``)."""
        with self._cv:
            self._raise_latched()
            if not self._buf:
                return
            while len(self._queue) >= self._max_pending:
                if self._drop_on_overflow:
                    self._queue.popleft()
                    self.dropped += 1
                    continue
                self._cv.wait()
                self._raise_latched()
            batch, self._buf = self._buf, []
            if self._seal is not None:
                try:
                    batch = self._seal(batch)
                except BaseException:
                    # the durability point failed (journal append/sync):
                    # put the items back so nothing is silently dropped,
                    # and surface the error to the committer — an
                    # unjournaled batch must never be acked
                    self._buf = batch + self._buf
                    raise
            self._queue.append(batch)
            self._ensure_thread()
            self._cv.notify_all()

    def drain(self) -> None:
        """Block until every buffered and queued write is durably applied
        (the read barrier; re-raises a latched flush failure)."""
        self.commit()
        with self._cv:
            while (self._queue or self._inflight) and self._error is None:
                self._cv.wait()
            self._raise_latched()

    def latch(self, error: BaseException) -> None:
        """Latch ``error`` from outside the flusher (the overlapped
        recovery thread, ISSUE 15): every later add/commit/drain raises,
        exactly as a terminal flush failure would — a wrapper whose
        startup replay failed must never silently serve writes over a
        store missing acked batches."""
        with self._cv:
            if self._error is None:
                self._error = error
            self._cv.notify_all()

    def close(self) -> None:
        """Drain (best-effort past a latched failure) and stop the
        flusher thread.  Does NOT close whatever ``flush`` writes to —
        that remains the embedder's resource."""
        try:
            self.drain()
        except RuntimeError:
            pass  # latched failure: nothing left to save
        except Exception:
            # a seal failure (journal device error) surfacing through
            # the drain's commit: the batch stays in the open buffer and
            # is lost with the process, but shutdown must still stop the
            # thread and let the embedder close its resources
            logger.exception("%s: drain failed during close", self._name)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=10.0)


class WriteBehindLinkDatabase(LinkDatabase):
    # backpressure: at most this many sealed batches may be pending
    # behind the flusher; commit() blocks past it, so a slow disk turns
    # into ingest backpressure instead of unbounded queue growth — and
    # every drain barrier (reads, scrapes) is bounded by a handful of
    # flush transactions rather than an arbitrary backlog
    _MAX_PENDING = 4

    def __init__(self, inner: LinkDatabase,
                 journal: Optional[LinkJournal] = None):
        self.inner = inner
        # durable redo log (ISSUE 10): sealed batches append here before
        # the ack; None preserves the legacy volatile-ack window
        self.journal = journal
        self._wb = WriteBehindBuffer(
            self._flush_batch, max_pending=self._MAX_PENDING,
            name="link write-behind", seal=self._seal_batch,
            retries=_flush_retries,
        )
        # recovery-overlap fence (ISSUE 15): set = no startup replay in
        # flight.  Writes (and the ingest-path reads that FEED writes)
        # wait on it; feed/monitoring reads deliberately do not — they
        # serve the replay's committed prefix behind the X-Recovering
        # staleness header.
        self._recovered = threading.Event()
        self._recovered.set()
        self._recovery_thread: Optional[threading.Thread] = None

    def _seal_batch(self, links: List[Link]):
        """Batch-sealing hook (runs inside ``commit()``): journal the
        batch durably and stamp it with its redo sequence.  THE
        durability point — everything after (enqueue, flush, ack) may
        crash and the batch still replays."""
        seq = None
        if self.journal is not None:
            seq = self.journal.append_batch(
                [encode_link(link) for link in links])
            faults.check_crash("post_journal_append")
        return (seq, links)

    def _flush_batch(self, sealed) -> None:
        seq, links = sealed
        plan = faults.active()
        if plan is not None:
            plan.check_crash("pre_flush")
            # chaos hook (DUKE_FAULTS flush_fail): a raised injection
            # exercises the retry ladder and then the latch exactly like
            # a real disk failure
            plan.check_flush("link write-behind")
        self.inner.assert_links(links)
        if plan is not None:
            plan.check_crash("mid_flush")
        self.inner.commit()
        if plan is not None:
            plan.check_crash("post_flush_pre_truncate")
        if self.journal is not None and seq is not None:
            self.journal.mark_applied(seq)

    def recover(self) -> int:
        """Replay journaled-but-unapplied batches into the durable store
        (startup only, before any concurrent use): the redo half of the
        crash-consistency contract.  Replays ride the same idempotent
        ``assert_links`` the flusher uses — an identical re-assert is a
        no-op, so a batch that WAS applied (crash before its watermark
        marker) converges instead of double-writing, and the feed sees
        no spurious timestamp bumps.  Returns the batch count replayed
        (counted in ``duke_recovery_replayed_total``)."""
        if self.journal is None:
            return 0
        batches = self.journal.unapplied()
        # replay in arrival order, coalesced into bounded transactions:
        # assert_links applies a concatenated run of batches identically
        # to applying them one by one (each key's final effective state
        # wins either way), and one watermark marker per chunk covers
        # every batch at or below it — a 10k-batch backlog replays in a
        # few dozen transactions instead of 10k commits
        chunk_size = 256
        # progress gauges (ISSUE 16): while /readyz still says
        # `recovering`, remaining counts down per chunk so an operator
        # can tell "almost done" from "wedged".  inc/dec (not set) so
        # concurrent per-workload overlapped recoveries sum correctly.
        telemetry.RECOVERY_REPLAY_REMAINING.inc(len(batches))  # dukecheck: ignore[DK502] startup recovery only, never per-batch
        for start in range(0, len(batches), chunk_size):
            chunk = batches[start:start + chunk_size]
            self.inner.assert_links(
                [decode_link(r) for _, rows in chunk for r in rows])
            self.inner.commit()
            self.journal.mark_applied(chunk[-1][0])
            telemetry.RECOVERY_REPLAY_APPLIED.inc(len(chunk))  # dukecheck: ignore[DK502] startup recovery only, once per 256-batch chunk
            telemetry.RECOVERY_REPLAY_REMAINING.dec(len(chunk))  # dukecheck: ignore[DK502] startup recovery only, once per 256-batch chunk
        self.journal.compact()
        if batches:
            telemetry.RECOVERY_REPLAYED.inc(len(batches))  # dukecheck: ignore[DK502] startup recovery only, never per-batch
            logger.warning(
                "recovered %d journaled link batch(es) the previous "
                "process never applied (crash between ack and flush)",
                len(batches),
            )
        return len(batches)

    def recover_async(self, scope: str = "") -> int:
        """Overlapped startup recovery (ISSUE 15): replay journaled-but-
        unapplied batches on a background thread while feed/monitoring
        reads serve the growing committed prefix.  Returns immediately
        with 0 when there is a backlog (the thread owns the count), or
        runs the (cheap) recovery inline when there is nothing to
        replay.

        Safety argument, in one place:

          * **Writes fence** — ``assert_link``/``assert_links``/
            ``commit`` (and the ingest-path reads below) block until
            replay completes, so no new batch can interleave with — or
            be journaled behind, yet applied before — the replayed
            backlog; arrival order is preserved exactly as serial
            recovery preserves it.
          * **Reads see a monotonic prefix** — replay applies whole
            batches in arrival order inside chunked transactions on its
            own sqlite connection, so a concurrent feed read observes
            complete batches only, each page extending the last (no
            torn batch, no duplicate — the idempotent assert skips
            identical re-asserts without a timestamp bump).
          * **Ingest-path reads fence too** — ``get_all_links_for`` /
            ``get_links_for_ids`` / ``get_all_links`` feed retraction
            and one-to-one decisions; a prefix read there could miss a
            link the replay was about to restore, so they wait exactly
            like writes.  The feed/monitoring reads
            (``get_changes_since``/``get_changes_page``/``count``)
            stay overlap-served.
          * **Failure latches** — a replay error latches the buffer
            (``WriteBehindBuffer.latch``), so the fence lifting can
            never silently serve writes over a store missing acked
            batches.

        The recovery scope is marked on THIS thread before returning,
        so a readiness probe can never observe the wrapper serving with
        the replay thread not yet started."""
        from . import journal as journal_mod

        if self.journal is None or self.journal.pending_batches == 0:
            return self.recover()
        self._recovered.clear()
        journal_mod.recovery_begin(scope)
        t = threading.Thread(
            target=self._recover_overlapped, args=(scope,), daemon=True,
            name="link-recovery",
        )
        self._recovery_thread = t
        t.start()
        return 0

    def _recover_overlapped(self, scope: str) -> None:
        from . import journal as journal_mod

        try:
            self.recover()
        except BaseException as e:
            logger.exception(
                "overlapped journal recovery failed; latching the "
                "wrapper (writes refused until restart)")
            self._wb.latch(e)
        finally:
            journal_mod.recovery_end(scope)
            self._recovered.set()

    @property
    def recovering(self) -> bool:
        """True while an overlapped startup replay is in flight (the
        write fence is up; reads serve the committed prefix)."""
        return not self._recovered.is_set()

    def _await_recovery(self) -> None:
        # the write fence: bounded by the replay duration (finite), and
        # a replay failure sets the event after latching, so waiters
        # surface the latched error instead of hanging
        if not self._recovered.is_set():
            self._recovered.wait()

    @property
    def flush_error(self) -> Optional[BaseException]:
        """The latched background-flush failure, or None (read lock-free
        by health probes: a dead persistence thread must be visible to
        orchestrators without waiting for a read to drain into it)."""
        return self._wb.error

    # test/introspection compatibility: the sealed-batch queue lives in
    # the shared buffer now
    @property
    def _queue(self) -> deque:
        return self._wb._queue  # dukecheck: ignore[DK202] test introspection handle; callers must hold _wb._cv to iterate

    # -- writes (buffered, arrival order; fenced during recovery) ------------

    def assert_link(self, link: Link) -> None:
        self._await_recovery()
        self._wb.add(link)

    def assert_links(self, links: List[Link]) -> None:
        self._await_recovery()
        self._wb.add_many(links)

    def commit(self) -> None:
        self._await_recovery()
        self._wb.commit()

    def drain(self) -> None:
        self._wb.drain()

    # -- reads (drain first; the ingest-path reads fence during recovery
    # because their results feed writes — see recover_async) -----------------

    def get_all_links_for(self, record_id: str) -> List[Link]:
        self._await_recovery()
        self.drain()
        return self.inner.get_all_links_for(record_id)

    def get_links_for_ids(self, record_ids) -> List[Link]:
        self._await_recovery()
        self.drain()
        return self.inner.get_links_for_ids(record_ids)

    def get_all_links(self) -> List[Link]:
        self._await_recovery()
        self.drain()
        return self.inner.get_all_links()

    def count(self) -> int:
        # deliberately NOT drained: count feeds /metrics and /stats
        # gauges, and a scrape must neither block on in-flight flush
        # transactions nor seal another thread's in-progress batch buffer
        # into a separate transaction.  The value trails the buffered
        # writes by at most a batch or two (exact again after any drain
        # point); every row-returning read keeps the full barrier.
        return self.inner.count()

    def get_changes_since(self, since: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_since(since)

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_page(since, limit)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        try:
            # an in-flight overlapped replay finishes first: interrupting
            # it is crash-safe (the journal keeps the backlog for the
            # next start) but a graceful shutdown should leave the store
            # caught up and the journal compacted
            self._await_recovery()
            self._wb.close()
        finally:
            # journal and inner store close even if the drain blew up —
            # fd/connection leaks on a failing shutdown would compound
            # the original failure.  A drained close leaves an EMPTY
            # journal (compacted when the watermark caught the head) —
            # the graceful-shutdown contract: nothing to replay next
            # start.
            if self.journal is not None:
                self.journal.close()
            self.inner.close()
