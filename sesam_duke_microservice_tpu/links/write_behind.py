"""Write-behind link persistence with a drain barrier on every read.

The persist phase used to flush each batch's link upserts synchronously
inside ``batch_done`` — serial with the next microbatch's encode phase.
This wrapper buffers writes in arrival order and flushes them on a single
background thread (one ``assert_links`` transaction + ``commit`` per
batch), so the durable flush overlaps the next microbatch's encode/device
work instead of extending the persist phase.

Consistency contract:

  * **Ordering** — writes apply in arrival order; ``commit()`` seals the
    current buffer as one batch and enqueues it (non-blocking).
  * **Drain barrier** — every row-returning read (``/datasets`` feed
    pages, the one-to-one flush's batched link fetch, delete-retraction
    lookups) drains buffered and in-flight writes first, so a reader can
    never observe a torn batch.  ``close()`` and the workload's
    corpus-snapshot save drain too.  ``count()`` alone is non-draining:
    it feeds monitoring gauges, which must not block on flush latency.
  * **Failure** — a background flush error latches the wrapper: the batch
    that failed was ONE transaction (all-or-nothing on the sqlite
    backend), and every subsequent write/commit/drain raises the latched
    error so ingest cannot silently run ahead of a dead link store.
    Recovery is a workload reload/restart, same as any persistent-store
    failure.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import List, Optional

from .base import Link, LinkDatabase

logger = logging.getLogger("links-write-behind")


class WriteBehindLinkDatabase(LinkDatabase):
    # backpressure: at most this many sealed batches may be pending
    # behind the flusher; commit() blocks past it, so a slow disk turns
    # into ingest backpressure instead of unbounded queue growth — and
    # every drain barrier (reads, scrapes) is bounded by a handful of
    # flush transactions rather than an arbitrary backlog
    _MAX_PENDING = 4

    def __init__(self, inner: LinkDatabase):
        self.inner = inner
        self._cv = threading.Condition()
        self._buf: List[Link] = []
        self._queue: deque = deque()
        self._inflight = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- worker --------------------------------------------------------------

    def _ensure_thread(self) -> None:
        # called with _cv held
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="link-flush"
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue:
                    return  # closed and drained
                batch = self._queue.popleft()
                self._inflight = True
            try:
                self.inner.assert_links(batch)
                self.inner.commit()
            except BaseException as e:  # latch: readers/writers must see it
                logger.exception("write-behind link flush failed")
                with self._cv:
                    self._error = e
                    self._inflight = False
                    self._queue.clear()
                    self._cv.notify_all()
                return
            with self._cv:
                self._inflight = False
                self._cv.notify_all()

    def _raise_latched(self) -> None:
        # called with _cv held
        if self._error is not None:
            raise RuntimeError(
                "link write-behind flush failed; the link store is stale "
                "(reload the workload to recover)"
            ) from self._error

    # -- writes (buffered, arrival order) ------------------------------------

    def assert_link(self, link: Link) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.append(link)

    def assert_links(self, links: List[Link]) -> None:
        with self._cv:
            self._raise_latched()
            self._buf.extend(links)

    def commit(self) -> None:
        """Seal the buffered writes as one batch and enqueue the flush;
        returns immediately unless the flusher is ``_MAX_PENDING`` batches
        behind (then it blocks — backpressure, not unbounded memory)."""
        with self._cv:
            self._raise_latched()
            if not self._buf:
                return
            while len(self._queue) >= self._MAX_PENDING:
                self._cv.wait()
                self._raise_latched()
            batch, self._buf = self._buf, []
            self._queue.append(batch)
            self._ensure_thread()
            self._cv.notify_all()

    def drain(self) -> None:
        """Block until every buffered and queued write is durably applied
        (the read barrier; re-raises a latched flush failure)."""
        self.commit()
        with self._cv:
            while (self._queue or self._inflight) and self._error is None:
                self._cv.wait()
            self._raise_latched()

    # -- reads (drain first) -------------------------------------------------

    def get_all_links_for(self, record_id: str) -> List[Link]:
        self.drain()
        return self.inner.get_all_links_for(record_id)

    def get_links_for_ids(self, record_ids) -> List[Link]:
        self.drain()
        return self.inner.get_links_for_ids(record_ids)

    def get_all_links(self) -> List[Link]:
        self.drain()
        return self.inner.get_all_links()

    def count(self) -> int:
        # deliberately NOT drained: count feeds /metrics and /stats
        # gauges, and a scrape must neither block on in-flight flush
        # transactions nor seal another thread's in-progress batch buffer
        # into a separate transaction.  The value trails the buffered
        # writes by at most a batch or two (exact again after any drain
        # point); every row-returning read keeps the full barrier.
        return self.inner.count()

    def get_changes_since(self, since: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_since(since)

    def get_changes_page(self, since: int, limit: int) -> List[Link]:
        self.drain()
        return self.inner.get_changes_page(since, limit)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        try:
            self.drain()
        except RuntimeError:
            pass  # latched failure: nothing left to save
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=10.0)
        self.inner.close()
