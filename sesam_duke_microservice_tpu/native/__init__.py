"""ctypes bindings for the C++ host comparators (duke_native.cpp).

Loads ``libduke_native.so`` from this directory, compiling it with g++ on
first use (no pybind11 in the image; plain C ABI + ctypes).  Every entry
point degrades gracefully: if the toolchain or library is unavailable —
or ``DUKE_TPU_NATIVE=0`` — ``available()`` is False and callers (the
comparators in core/comparators.py) keep their pure-Python path, which
doubles as the parity oracle (tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from ..telemetry.env import env_flag

logger = logging.getLogger("duke-tpu-native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "duke_native.cpp")
_LIB = os.path.join(_HERE, "libduke_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # compile to a private temp name, then rename: os.rename is atomic on
    # POSIX, so a concurrent process never dlopens a half-written library
    tmp = _LIB + f".tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native comparator build failed (%s); using pure Python", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not env_flag("DUKE_TPU_NATIVE", True):
            return None
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.warning("could not load %s (%s); using pure Python", _LIB, e)
            return None
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f64p = ctypes.POINTER(ctypes.c_double)
        lib.duke_lev_sim_batch.argtypes = [u32p, i64p, u32p, i64p,
                                           ctypes.c_int64, f64p]
        lib.duke_lev_sim_batch.restype = None
        lib.duke_jaro_winkler_batch.argtypes = [
            u32p, i64p, u32p, i64p, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_int64, f64p]
        lib.duke_jaro_winkler_batch.restype = None
        lib.duke_weighted_lev_batch.argtypes = [
            u32p, i64p, u32p, i64p, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, f64p]
        lib.duke_weighted_lev_batch.restype = None
        lib.duke_lev_distance.argtypes = [u32p, ctypes.c_int64, u32p,
                                          ctypes.c_int64]
        lib.duke_lev_distance.restype = ctypes.c_int64
        lib.duke_embed_batch.argtypes = [
            u32p, i64p, ctypes.POINTER(ctypes.c_uint64), i64p,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float)]
        lib.duke_embed_batch.restype = None
        lib.duke_fnv1a64_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), i64p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.duke_fnv1a64_batch.restype = None
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.duke_gram_set_batch.argtypes = [
            u32p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i32p, i32p]
        lib.duke_gram_set_batch.restype = None
        # scalar entry points take the UTF-32 bytes object directly
        # (c_char_p), skipping numpy packing
        cc = ctypes.c_char_p
        i64 = ctypes.c_int64
        dbl = ctypes.c_double
        lib.duke_lev_sim.argtypes = [cc, i64, cc, i64]
        lib.duke_lev_sim.restype = dbl
        lib.duke_jaro_winkler.argtypes = [cc, i64, cc, i64, dbl, dbl, i64]
        lib.duke_jaro_winkler.restype = dbl
        lib.duke_weighted_lev.argtypes = [cc, i64, cc, i64, dbl, dbl, dbl]
        lib.duke_weighted_lev.restype = dbl
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


_U32P = ctypes.POINTER(ctypes.c_uint32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_F64P = ctypes.POINTER(ctypes.c_double)


def _pack(strings: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """UTF-32 codepoint buffer + int64 offsets (len n+1)."""
    offsets = np.zeros(len(strings) + 1, dtype=np.int64)
    chunks = []
    total = 0
    for i, s in enumerate(strings):
        chunk = s.encode("utf-32-le", "surrogatepass")
        chunks.append(chunk)
        total += len(chunk) // 4
        offsets[i + 1] = total
    if total:
        buf = np.frombuffer(b"".join(chunks), dtype="<u4")
    else:
        buf = np.zeros(1, dtype=np.uint32)  # valid pointer for empty input
    return buf, offsets


def _ptrs(buf: np.ndarray, off: np.ndarray):
    return buf.ctypes.data_as(_U32P), off.ctypes.data_as(_I64P)


def fnv1a64_bytes_batch(bufs: Sequence[bytes]) -> np.ndarray:
    """Bulk FNV-1a64 over pre-encoded UTF-8 buffers -> (N,) uint64.

    Bit-identical to ``ops.features.fnv1a64`` (which hashes the UTF-8
    encoding); the ingest path hashes every value + q-gram + token per
    record, so this one C pass replaces the numpy grouped-fold hot spot.
    """
    lib = _load()
    assert lib is not None
    n = len(bufs)
    off = np.zeros(n + 1, dtype=np.int64)
    total = 0
    for i, b in enumerate(bufs):
        total += len(b)
        off[i + 1] = total
    buf = (np.frombuffer(b"".join(bufs), dtype=np.uint8) if total
           else np.zeros(1, dtype=np.uint8))
    out = np.empty((n,), dtype=np.uint64)
    lib.duke_fnv1a64_batch(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        off.ctypes.data_as(_I64P), n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out


def gram_set_batch(values: Sequence[str], q: int,
                   max_grams: int, set_pad: int) -> Tuple[np.ndarray, np.ndarray]:
    """Bulk q-gram set ids: ((N, max_grams) int32 sorted-distinct folded
    gram hashes padded with ``set_pad``, (N,) int32 counts).  Bit-identical
    to the Python path in ops.features (qgrams + hash + sorted(set))."""
    lib = _load()
    assert lib is not None
    buf, off = _pack(values)
    n = len(values)
    grams = np.full((n, max_grams), set_pad, dtype=np.int32)
    counts = np.zeros((n,), dtype=np.int32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.duke_gram_set_batch(
        *_ptrs(buf, off), n, q, max_grams,
        grams.ctypes.data_as(i32p), counts.ctypes.data_as(i32p),
    )
    return grams, counts


def lev_sim_batch(a: Sequence[str], b: Sequence[str]) -> np.ndarray:
    lib = _load()
    assert lib is not None and len(a) == len(b)
    abuf, aoff = _pack(a)
    bbuf, boff = _pack(b)
    out = np.empty(len(a), dtype=np.float64)
    lib.duke_lev_sim_batch(*_ptrs(abuf, aoff), *_ptrs(bbuf, boff),
                           len(a), out.ctypes.data_as(_F64P))
    return out


def jaro_winkler_batch(a: Sequence[str], b: Sequence[str], *,
                       prefix_scale: float = 0.1,
                       boost_threshold: float = 0.7,
                       max_prefix: int = 4) -> np.ndarray:
    lib = _load()
    assert lib is not None and len(a) == len(b)
    abuf, aoff = _pack(a)
    bbuf, boff = _pack(b)
    out = np.empty(len(a), dtype=np.float64)
    lib.duke_jaro_winkler_batch(*_ptrs(abuf, aoff), *_ptrs(bbuf, boff),
                                len(a), prefix_scale, boost_threshold,
                                max_prefix, out.ctypes.data_as(_F64P))
    return out


def weighted_lev_batch(a: Sequence[str], b: Sequence[str], *,
                       digit_weight: float = 2.0, letter_weight: float = 1.0,
                       other_weight: float = 1.0) -> np.ndarray:
    lib = _load()
    assert lib is not None and len(a) == len(b)
    abuf, aoff = _pack(a)
    bbuf, boff = _pack(b)
    out = np.empty(len(a), dtype=np.float64)
    lib.duke_weighted_lev_batch(*_ptrs(abuf, aoff), *_ptrs(bbuf, boff),
                                len(a), digit_weight, letter_weight,
                                other_weight, out.ctypes.data_as(_F64P))
    return out


def embed_batch(value_strings: Sequence[str], salts: np.ndarray,
                rec_off: np.ndarray, dim: int) -> np.ndarray:
    """Hashed-n-gram record embeddings (ops.encoder parity, bulk).

    ``value_strings`` are the already padded+lowercased per-value strings
    (concatenated across records), ``salts`` the per-value uint64 property
    salts, ``rec_off`` the int64 record->value-range offsets (n_rec+1).
    Returns (n_rec, dim) float32, rows L2-normalized.
    """
    lib = _load()
    assert lib is not None
    buf, off = _pack(value_strings)
    salts = np.ascontiguousarray(salts, dtype=np.uint64)
    rec_off = np.ascontiguousarray(rec_off, dtype=np.int64)
    n_rec = len(rec_off) - 1
    out = np.zeros((n_rec, dim), dtype=np.float32)
    lib.duke_embed_batch(
        *_ptrs(buf, off),
        salts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        rec_off.ctypes.data_as(_I64P), n_rec, dim,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


def lev_sim(a: str, b: str) -> float:
    lib = _load()
    return lib.duke_lev_sim(a.encode("utf-32-le", "surrogatepass"), len(a),
                            b.encode("utf-32-le", "surrogatepass"), len(b))


def jaro_winkler(a: str, b: str, prefix_scale: float = 0.1,
                 boost_threshold: float = 0.7, max_prefix: int = 4) -> float:
    lib = _load()
    return lib.duke_jaro_winkler(a.encode("utf-32-le", "surrogatepass"), len(a),
                                 b.encode("utf-32-le", "surrogatepass"), len(b),
                                 prefix_scale, boost_threshold, max_prefix)


def weighted_lev(a: str, b: str, digit_weight: float = 2.0,
                 letter_weight: float = 1.0,
                 other_weight: float = 1.0) -> float:
    lib = _load()
    return lib.duke_weighted_lev(a.encode("utf-32-le", "surrogatepass"), len(a),
                                 b.encode("utf-32-le", "surrogatepass"), len(b),
                                 digit_weight, letter_weight, other_weight)


def lev_distance(a: str, b: str) -> int:
    lib = _load()
    assert lib is not None
    abuf = np.frombuffer(a.encode("utf-32-le", "surrogatepass"), dtype="<u4") if a else np.zeros(1, dtype=np.uint32)
    bbuf = np.frombuffer(b.encode("utf-32-le", "surrogatepass"), dtype="<u4") if b else np.zeros(1, dtype=np.uint32)
    return int(lib.duke_lev_distance(
        abuf.ctypes.data_as(_U32P), len(a), bbuf.ctypes.data_as(_U32P), len(b)))
