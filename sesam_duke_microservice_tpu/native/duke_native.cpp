// Native host hot path: batched string comparators.
//
// The reference's hot loop is per-pair per-property Comparator.compare inside
// the Duke 1.2 jar (driven at App.java:1005/1159; SURVEY.md section 3.2 "hot
// loops").  In this framework the TPU scores candidate blocks, but host paths
// still burn CPU on scalar string comparison — the host reference engine
// (engine/processor.py) and the device matcher's host-exact finalization both
// dispatch through core/comparators.py, whose Levenshtein/JaroWinkler/
// WeightedLevenshtein route here via the SCALAR entry points at the bottom of
// this file.  The *_batch entry points are the library's bulk API (one call,
// many pairs — amortizes the FFI boundary ~10x over scalar) for tooling and
// bulk rescoring; tests/test_native.py pins both shapes to the pure-Python
// oracles.  Levenshtein is Myers/Hyyro bit-parallel for patterns <= 64
// codepoints with a plain-DP fallback.
//
// Strings cross the boundary as UTF-32 codepoints (uint32) in one contiguous
// buffer with an int64 offsets array: pair i is a[a_off[i]:a_off[i+1]] vs
// b[b_off[i]:b_off[i+1]].  Pure C ABI for ctypes.

#include <algorithm>
#include <cstdint>
#include <cmath>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Levenshtein distance, exact.  Myers bit-parallel O(n) per text char for
// patterns up to 64 codepoints (Hyyro's formulation); banded-free plain DP
// rows otherwise.  Both return the exact distance.

int64_t lev_plain(const uint32_t* s1, int64_t n1, const uint32_t* s2,
                  int64_t n2) {
    std::vector<int64_t> prev(n2 + 1), cur(n2 + 1);
    for (int64_t j = 0; j <= n2; ++j) prev[j] = j;
    for (int64_t i = 1; i <= n1; ++i) {
        cur[0] = i;
        const uint32_t c1 = s1[i - 1];
        for (int64_t j = 1; j <= n2; ++j) {
            const int64_t cost = (c1 == s2[j - 1]) ? 0 : 1;
            cur[j] = std::min({prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost});
        }
        std::swap(prev, cur);
    }
    return prev[n2];
}

int64_t lev_myers64(const uint32_t* pat, int64_t m, const uint32_t* text,
                    int64_t n) {
    // peq: ASCII fast path in a flat table, map for the rest
    uint64_t peq_ascii[128];
    std::memset(peq_ascii, 0, sizeof(peq_ascii));
    std::unordered_map<uint32_t, uint64_t> peq_other;
    for (int64_t i = 0; i < m; ++i) {
        const uint32_t c = pat[i];
        if (c < 128) peq_ascii[c] |= 1ULL << i;
        else peq_other[c] |= 1ULL << i;
    }
    uint64_t pv = ~0ULL, mv = 0;
    int64_t score = m;
    const uint64_t high = 1ULL << (m - 1);
    for (int64_t j = 0; j < n; ++j) {
        const uint32_t c = text[j];
        uint64_t eq;
        if (c < 128) {
            eq = peq_ascii[c];
        } else {
            auto it = peq_other.find(c);
            eq = (it == peq_other.end()) ? 0 : it->second;
        }
        const uint64_t xv = eq | mv;
        const uint64_t xh = (((eq & pv) + pv) ^ pv) | eq;
        uint64_t ph = mv | ~(xh | pv);
        uint64_t mh = pv & xh;
        if (ph & high) ++score;
        if (mh & high) --score;
        ph = (ph << 1) | 1;
        mh = mh << 1;
        pv = mh | ~(xv | ph);
        mv = ph & xv;
    }
    return score;
}

int64_t lev_distance(const uint32_t* s1, int64_t n1, const uint32_t* s2,
                     int64_t n2) {
    if (n1 == 0) return n2;
    if (n2 == 0) return n1;
    // pattern = shorter string for the bit-parallel path
    const uint32_t* pat = s1;
    int64_t m = n1;
    const uint32_t* text = s2;
    int64_t n = n2;
    if (m > n) { std::swap(pat, text); std::swap(m, n); }
    if (m <= 64) return lev_myers64(pat, m, text, n);
    return lev_plain(pat, m, text, n);
}

// Duke Levenshtein similarity semantics (core/comparators.py Levenshtein):
// equal -> 1; empty shorter -> 0; length-ratio early exit -> 0;
// sim = 1 - min(dist, shorter)/shorter.
double lev_sim(const uint32_t* a, int64_t na, const uint32_t* b, int64_t nb) {
    if (na == nb && std::memcmp(a, b, na * sizeof(uint32_t)) == 0) return 1.0;
    const int64_t shorter = std::min(na, nb);
    const int64_t longer = std::max(na, nb);
    if (shorter == 0) return 0.0;
    if ((longer - shorter) * 2 > shorter) return 0.0;
    const int64_t dist = std::min(lev_distance(a, na, b, nb), shorter);
    return 1.0 - static_cast<double>(dist) / static_cast<double>(shorter);
}

// ---------------------------------------------------------------------------
// Jaro-Winkler (core/comparators.py _jaro/JaroWinkler parity).

double jaro(const uint32_t* s1, int64_t n1, const uint32_t* s2, int64_t n2,
            std::vector<uint8_t>& matched2, std::vector<uint32_t>& m1) {
    if (n1 == 0 || n2 == 0) return 0.0;
    const int64_t window = std::max<int64_t>(std::max(n1, n2) / 2 - 1, 0);
    matched2.assign(n2, 0);
    m1.clear();
    int64_t matches = 0;
    for (int64_t i = 0; i < n1; ++i) {
        const uint32_t c = s1[i];
        const int64_t lo = std::max<int64_t>(0, i - window);
        const int64_t hi = std::min(n2, i + window + 1);
        for (int64_t j = lo; j < hi; ++j) {
            if (!matched2[j] && s2[j] == c) {
                matched2[j] = 1;
                ++matches;
                m1.push_back(c);
                break;
            }
        }
    }
    if (matches == 0) return 0.0;
    int64_t transpositions = 0;
    int64_t k = 0;
    for (int64_t j = 0; j < n2; ++j) {
        if (matched2[j]) {
            if (m1[k] != s2[j]) ++transpositions;
            ++k;
        }
    }
    transpositions /= 2;
    const double m = static_cast<double>(matches);
    return (m / n1 + m / n2 + (m - transpositions) / m) / 3.0;
}

double jaro_winkler(const uint32_t* a, int64_t na, const uint32_t* b,
                   int64_t nb, double prefix_scale, double boost_threshold,
                   int64_t max_prefix, std::vector<uint8_t>& matched2,
                   std::vector<uint32_t>& m1) {
    if (na == nb && std::memcmp(a, b, na * sizeof(uint32_t)) == 0) return 1.0;
    const double j = jaro(a, na, b, nb, matched2, m1);
    if (j < boost_threshold) return j;
    int64_t prefix = 0;
    const int64_t lim = std::min(na, nb);
    for (int64_t i = 0; i < lim; ++i) {
        if (a[i] != b[i] || prefix == max_prefix) break;
        ++prefix;
    }
    return j + prefix * prefix_scale * (1.0 - j);
}

// ---------------------------------------------------------------------------
// Weighted Levenshtein (core/comparators.py WeightedLevenshtein parity):
// per-character class weights; substitution costs max(w1, w2).

double wl_weight(uint32_t c, double dw, double lw, double ow) {
    // ASCII classes only, matching Python str.isdigit/isalpha for the ASCII
    // range the comparator is used on (id-ish fields); non-ASCII letters are
    // classed by a conservative alpha check
    if (c >= '0' && c <= '9') return dw;
    if ((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z')) return lw;
    if (c >= 128) return lw;  // treat non-ASCII as letters (Python isalpha-ish)
    return ow;
}

double weighted_lev_sim(const uint32_t* a, int64_t na, const uint32_t* b,
                       int64_t nb, double dw, double lw, double ow) {
    if (na == nb && std::memcmp(a, b, na * sizeof(uint32_t)) == 0) return 1.0;
    const int64_t shorter = std::min(na, nb);
    if (shorter == 0) return 0.0;
    std::vector<double> prev(nb + 1), cur(nb + 1);
    prev[0] = 0.0;
    for (int64_t j = 1; j <= nb; ++j)
        prev[j] = prev[j - 1] + wl_weight(b[j - 1], dw, lw, ow);
    for (int64_t i = 1; i <= na; ++i) {
        const double w1 = wl_weight(a[i - 1], dw, lw, ow);
        cur[0] = prev[0] + w1;
        for (int64_t j = 1; j <= nb; ++j) {
            const double w2 = wl_weight(b[j - 1], dw, lw, ow);
            const double sub = (a[i - 1] == b[j - 1]) ? 0.0 : std::max(w1, w2);
            cur[j] = std::min({prev[j] + w1, cur[j - 1] + w2, prev[j - 1] + sub});
        }
        std::swap(prev, cur);
    }
    const double dist = std::min(prev[nb], static_cast<double>(shorter));
    return 1.0 - dist / shorter;
}

// -- hashed-n-gram record embeddings (ops/encoder.py parity) ----------------
// Trigram window hashing with the exact constants of the Python/numpy path
// (ops.encoder._H_MULT/_FM1/_FM2): one odd multiplier per window position,
// xor'd with a per-(property)-salt, then a murmur3-style finalizer.  The
// Python implementation is the parity oracle (tests/test_native.py).

constexpr uint64_t kEmbMult0 = 0x9E3779B97F4A7C15ULL;
constexpr uint64_t kEmbMult1 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t kEmbMult2 = 0x165667B19E3779F9ULL;
constexpr uint64_t kEmbFm1 = 0xFF51AFD7ED558CCDULL;
constexpr uint64_t kEmbFm2 = 0xC4CEB9FE1A85EC53ULL;

inline uint64_t emb_fmix(uint64_t h) {
    h ^= h >> 33;
    h *= kEmbFm1;
    h ^= h >> 29;
    h *= kEmbFm2;
    h ^= h >> 32;
    return h;
}

}  // namespace

extern "C" {

// One embedding per record.  cp_buf holds the concatenated (already padded
// + lowercased, see ops.encoder) codepoints of every value; val_off[v] /
// val_off[v+1] bound value v; salts[v] is its property salt; rec_off[r]
// bounds record r's value range.  out is (n_rec, dim) float32, L2
// normalized per row.
void duke_embed_batch(const uint32_t* cp_buf, const int64_t* val_off,
                      const uint64_t* salts, const int64_t* rec_off,
                      int64_t n_rec, int64_t dim, float* out) {
    std::unordered_map<uint64_t, int64_t> counts;
    std::vector<uint32_t> tiny;
    for (int64_t r = 0; r < n_rec; ++r) {
        counts.clear();
        for (int64_t v = rec_off[r]; v < rec_off[r + 1]; ++v) {
            const uint32_t* cp = cp_buf + val_off[v];
            int64_t len = val_off[v + 1] - val_off[v];
            if (len < 3) {  // zero-pad to one window (numpy np.pad parity)
                tiny.assign(3, 0);
                for (int64_t i = 0; i < len; ++i) tiny[i] = cp[i];
                cp = tiny.data();
                len = 3;
            }
            const uint64_t salt = salts[v];
            for (int64_t i = 0; i + 2 < len; ++i) {
                uint64_t h = salt;
                h ^= static_cast<uint64_t>(cp[i]) * kEmbMult0;
                h ^= static_cast<uint64_t>(cp[i + 1]) * kEmbMult1;
                h ^= static_cast<uint64_t>(cp[i + 2]) * kEmbMult2;
                ++counts[emb_fmix(h)];
            }
        }
        float* vec = out + r * dim;
        std::fill(vec, vec + dim, 0.0f);
        double sq = 0.0;
        for (const auto& kv : counts) {
            const uint64_t h = kv.first;
            const int64_t bucket = static_cast<int64_t>(h % static_cast<uint64_t>(dim));
            const float sign = ((h >> 32) & 1ULL) ? 1.0f : -1.0f;
            vec[bucket] += sign * std::sqrt(static_cast<float>(kv.second));
        }
        for (int64_t d = 0; d < dim; ++d) sq += static_cast<double>(vec[d]) * vec[d];
        if (sq > 0.0) {
            const float inv = static_cast<float>(1.0 / std::sqrt(sq));
            for (int64_t d = 0; d < dim; ++d) vec[d] *= inv;
        }
    }
}

void duke_lev_sim_batch(const uint32_t* a_buf, const int64_t* a_off,
                        const uint32_t* b_buf, const int64_t* b_off,
                        int64_t n, double* out) {
    for (int64_t i = 0; i < n; ++i) {
        out[i] = lev_sim(a_buf + a_off[i], a_off[i + 1] - a_off[i],
                         b_buf + b_off[i], b_off[i + 1] - b_off[i]);
    }
}

void duke_jaro_winkler_batch(const uint32_t* a_buf, const int64_t* a_off,
                             const uint32_t* b_buf, const int64_t* b_off,
                             int64_t n, double prefix_scale,
                             double boost_threshold, int64_t max_prefix,
                             double* out) {
    std::vector<uint8_t> matched2;
    std::vector<uint32_t> m1;
    for (int64_t i = 0; i < n; ++i) {
        out[i] = jaro_winkler(a_buf + a_off[i], a_off[i + 1] - a_off[i],
                              b_buf + b_off[i], b_off[i + 1] - b_off[i],
                              prefix_scale, boost_threshold, max_prefix,
                              matched2, m1);
    }
}

void duke_weighted_lev_batch(const uint32_t* a_buf, const int64_t* a_off,
                             const uint32_t* b_buf, const int64_t* b_off,
                             int64_t n, double digit_weight,
                             double letter_weight, double other_weight,
                             double* out) {
    for (int64_t i = 0; i < n; ++i) {
        out[i] = weighted_lev_sim(a_buf + a_off[i], a_off[i + 1] - a_off[i],
                                  b_buf + b_off[i], b_off[i + 1] - b_off[i],
                                  digit_weight, letter_weight, other_weight);
    }
}

int64_t duke_lev_distance(const uint32_t* a, int64_t na, const uint32_t* b,
                          int64_t nb) {
    return lev_distance(a, na, b, nb);
}

// Bulk q-gram set extraction (ops.features GRAM_SET): for each value
// (UTF-32 codepoint range), hash every q-codepoint window — the whole
// value when shorter than q — with FNV-1a64 over the window's UTF-8
// encoding, fold to int32 ((h ^ h>>32) low word, two's complement),
// dedupe + sort ascending (signed), truncate to max_grams.  out_grams is
// (n, max_grams) prefilled with the SET_PAD sentinel; bit-identical to
// the Python path (qgrams + fnv1a64_batch + sorted(set(...))) —
// differential-tested in tests/test_native.py.
void duke_gram_set_batch(const uint32_t* buf, const int64_t* off, int64_t n,
                         int64_t q, int64_t max_grams, int32_t* out_grams,
                         int32_t* out_counts) {
    constexpr uint64_t kOffset = 0xCBF29CE484222325ULL;
    constexpr uint64_t kPrime = 0x100000001B3ULL;
    std::vector<int32_t> ids;
    for (int64_t i = 0; i < n; ++i) {
        const uint32_t* cp = buf + off[i];
        const int64_t len = off[i + 1] - off[i];
        out_counts[i] = 0;
        if (len == 0) continue;
        const int64_t win = len < q ? len : q;
        const int64_t n_win = len < q ? 1 : len - q + 1;
        ids.clear();
        for (int64_t w = 0; w < n_win; ++w) {
            uint64_t h = kOffset;
            for (int64_t j = 0; j < win; ++j) {
                // inline UTF-8 encoding of one codepoint (surrogatepass:
                // D800-DFFF take the normal 3-byte form, matching
                // str.encode("utf-8", "surrogatepass"))
                const uint32_t c = cp[w + j];
                if (c < 0x80) {
                    h = (h ^ c) * kPrime;
                } else if (c < 0x800) {
                    h = (h ^ (0xC0 | (c >> 6))) * kPrime;
                    h = (h ^ (0x80 | (c & 0x3F))) * kPrime;
                } else if (c < 0x10000) {
                    h = (h ^ (0xE0 | (c >> 12))) * kPrime;
                    h = (h ^ (0x80 | ((c >> 6) & 0x3F))) * kPrime;
                    h = (h ^ (0x80 | (c & 0x3F))) * kPrime;
                } else {
                    h = (h ^ (0xF0 | (c >> 18))) * kPrime;
                    h = (h ^ (0x80 | ((c >> 12) & 0x3F))) * kPrime;
                    h = (h ^ (0x80 | ((c >> 6) & 0x3F))) * kPrime;
                    h = (h ^ (0x80 | (c & 0x3F))) * kPrime;
                }
            }
            ids.push_back(static_cast<int32_t>(
                static_cast<uint32_t>((h ^ (h >> 32)) & 0xFFFFFFFFULL)));
        }
        std::sort(ids.begin(), ids.end());
        ids.erase(std::unique(ids.begin(), ids.end()), ids.end());
        const int64_t count = static_cast<int64_t>(ids.size()) < max_grams
                                  ? static_cast<int64_t>(ids.size())
                                  : max_grams;
        int32_t* row = out_grams + i * max_grams;
        for (int64_t g = 0; g < count; ++g) row[g] = ids[g];
        out_counts[i] = static_cast<int32_t>(count);
    }
}

// Bulk FNV-1a64 over UTF-8 byte ranges: the ingest hot path hashes every
// value plus every q-gram/token per record (ops.features), and even the
// vectorized numpy fold costs ~45 us per KB of grouped padding work.
// buf/off follow the batch packing convention (off has n+1 entries).
// Bit-identical to ops.features.fnv1a64 (differential-tested).
void duke_fnv1a64_batch(const uint8_t* buf, const int64_t* off, int64_t n,
                        uint64_t* out) {
    constexpr uint64_t kOffset = 0xCBF29CE484222325ULL;
    constexpr uint64_t kPrime = 0x100000001B3ULL;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t h = kOffset;
        for (int64_t p = off[i]; p < off[i + 1]; ++p) {
            h ^= buf[p];
            h *= kPrime;
        }
        out[i] = h;
    }
}

// Scalar entry points for the per-pair comparator dispatch: take the raw
// UTF-32 byte buffers straight from str.encode() so the Python side skips
// numpy packing (the batch functions amortize that cost; a scalar call
// cannot).

double duke_lev_sim(const uint32_t* a, int64_t na, const uint32_t* b,
                    int64_t nb) {
    return lev_sim(a, na, b, nb);
}

double duke_jaro_winkler(const uint32_t* a, int64_t na, const uint32_t* b,
                         int64_t nb, double prefix_scale,
                         double boost_threshold, int64_t max_prefix) {
    std::vector<uint8_t> matched2;
    std::vector<uint32_t> m1;
    return jaro_winkler(a, na, b, nb, prefix_scale, boost_threshold,
                        max_prefix, matched2, m1);
}

double duke_weighted_lev(const uint32_t* a, int64_t na, const uint32_t* b,
                         int64_t nb, double digit_weight,
                         double letter_weight, double other_weight) {
    return weighted_lev_sim(a, na, b, nb, digit_weight, letter_weight,
                            other_weight);
}

}  // extern "C"
