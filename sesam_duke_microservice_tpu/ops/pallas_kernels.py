"""Pallas TPU kernels for the pairwise hot ops.

The XLA path (ops.pairwise + ops.scoring._pair_expand) materializes expanded
``(Q*C, L)`` codepoint operands in HBM for every corpus chunk — O(Q*C*L)
memory traffic for O(Q*C*L) compute.  The kernels here tile the pair matrix
flash-attention style instead: a grid over (query-tile x corpus-tile) loads
``O(T*L)`` characters into VMEM once and computes the full ``(TQ, TC)``
distance tile on-chip, so HBM traffic drops from O(Q*C*L) to
O((Q/TQ + C/TC) * T * L) while all O(Q*C*L) bit-parallel work stays in
VMEM/registers.  This is the "comparators become batched Pallas kernels"
component of the north-star plan (BASELINE.json) — the reference's scalar
per-pair ``Comparator.compare`` hot loop (reference App.java:1005 ->
Duke Processor.compare) becomes one device program.

Kernel inventory:

  * ``myers_distance_tiles`` — batched Levenshtein distance over all
    query x corpus pairs via Myers/Hyyro bit-parallel DP: one uint32 word
    per pair for patterns <= 32 codepoints, and an N-word
    carry-propagated variant (Hyyro's block formulation) up to
    ``MYERS_MAX_CHARS`` = 256, so default 64-char configs AND long-text
    schemas (128/256 chars) stay on the Pallas path.  Differentially
    tested against ``ops.pairwise`` and the scalar oracle.
  * ``myers_distance_gathered`` — the same DP in the ANN-rescoring layout:
    candidate c of query q is a specific gathered row, so the candidate
    axis rides the lanes and text chars differ per pair.
  * ``set_intersection_tiles`` — |A ∩ B| for all query x corpus pairs of
    hashed id sets (q-grams / tokens): dense equality compare in VMEM,
    O(T*G) HBM traffic per tile instead of the XLA path's expanded
    (Q*C, G) pair operands.  Backs ``set_sim_tiles`` (QGram / Jaccard /
    Dice).
  * ``jaro_winkler_sim_tiles`` — Jaro-Winkler over all pairs via matched-
    position uint32 bitmasks (greedy window matching + lowest-bit
    transposition walk); 31x the flat XLA path end-to-end at the
    production scan config (BASELINE.md).  Differentially tested against
    the scalar comparator oracle.

Enabling: ``pallas_enabled()`` — env ``DUKE_TPU_PALLAS`` ("1" force on,
"0" force off); default on only when the active JAX backend is TPU.  On
non-TPU backends kernels run in interpreter mode (slow, test-only).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..telemetry.env import env_flag

try:  # pltpu is importable on all platforms; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover - non-TPU builds without pltpu
    pltpu = None
    _VMEM = None


def _backend() -> str:
    try:
        return jax.default_backend()
    except Exception:  # pragma: no cover
        return "cpu"


def pallas_enabled() -> bool:
    """Should the scoring program route char kernels through Pallas?"""
    return env_flag("DUKE_TPU_PALLAS", _backend() == "tpu")


def _interpret() -> bool:
    return _backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# Shared operand staging for every pair-matrix tile kernel (Myers, JW, set
# intersection): queries row-major (Q, W) + lengths, corpus transposed
# (W, C) + lengths, padded to tile multiples.  One place for the padding
# and BlockSpec conventions so a layout fix cannot miss a kernel family.


def _pair_tile_specs(w_q: int, w_c: int, tile_q: int, tile_c: int):
    return [
        pl.BlockSpec((tile_q, w_q), lambda i, j: (i, 0), memory_space=_VMEM),
        pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0), memory_space=_VMEM),
        pl.BlockSpec((w_c, tile_c), lambda i, j: (0, j), memory_space=_VMEM),
        pl.BlockSpec((1, tile_c), lambda i, j: (0, j), memory_space=_VMEM),
    ]


def _stage_pair_operands(qx, qn, cx, cn, *, tile_q_cap: int,
                         tile_c_cap: int):
    """Pad to tile multiples; returns (qp_arr, qn2, cxt, cn2, tile_q,
    tile_c).  Padded rows compute garbage the caller masks out."""
    q, w = qx.shape
    c = cx.shape[0]
    tile_q = min(tile_q_cap, _round_up(max(q, 1), 8))
    tile_c = min(tile_c_cap, _round_up(max(c, 1), 128))
    qp = _round_up(max(q, 1), tile_q)
    cp = _round_up(max(c, 1), tile_c)
    qa = jnp.zeros((qp, w), jnp.int32).at[:q].set(qx)
    qn2 = jnp.zeros((qp, 1), jnp.int32).at[:q, 0].set(qn)
    cxt = jnp.zeros((w, cp), jnp.int32).at[:, :c].set(cx.T)
    cn2 = jnp.zeros((1, cp), jnp.int32).at[0, :c].set(cn)
    return qa, qn2, cxt, cn2, tile_q, tile_c


# -- Myers bit-parallel Levenshtein, tiled over the pair matrix --------------


def _myers_word_init(ql):
    """One-word DP init: (pv0, hibit) for pattern lengths <= 32.

    min/max on int32 (Mosaic lacks unsigned vector min), then cast to
    uint32 for the shifts.  bit j of pv0 set iff j < ql (guard the
    undefined <<32).
    """
    one = jnp.uint32(1)
    full = jnp.uint32(0xFFFFFFFF)
    pv0 = jnp.where(
        ql >= 32, full, (one << jnp.minimum(ql, 31).astype(jnp.uint32)) - one
    )
    hibit = one << (jnp.maximum(ql, 1) - 1).astype(jnp.uint32)
    return pv0, hibit


def _myers_word_step(eq, pv, mv, score, active, hibit):
    """One text step of the one-word Myers recurrence (shared by the
    cross-product and gathered kernels — one copy of the math)."""
    one = jnp.uint32(1)
    xv = eq | mv
    xh = (((eq & pv) + pv) ^ pv) | eq
    ph = mv | ~(xh | pv)
    mh = pv & xh
    score = score + jnp.where(active & ((ph & hibit) != 0), 1, 0)
    score = score - jnp.where(active & ((mh & hibit) != 0), 1, 0)
    ph = (ph << one) | one
    mh = mh << one
    pv = jnp.where(active, mh | ~(xv | ph), pv)
    mv = jnp.where(active, ph & xv, mv)
    return pv, mv, score


def _myers_tile_kernel(qc_ref, ql_ref, cct_ref, cl_ref, out_ref, *, L: int):
    """One (TQ, TC) distance tile.

    qc_ref:  (TQ, L)  query codepoints (pattern), 0-padded
    ql_ref:  (TQ, 1)  query lengths
    cct_ref: (L, TC)  corpus codepoints, transposed (text)
    cl_ref:  (1, TC)  corpus lengths
    out_ref: (TQ, TC) int32 distances
    """
    tq = qc_ref.shape[0]
    tc = cct_ref.shape[1]
    qc = qc_ref[...]                      # (TQ, L)
    ql = ql_ref[...][:, :1]               # (TQ, 1)
    cl = cl_ref[...][:1, :]               # (1, TC)

    pv0, hibit = _myers_word_init(ql)     # (TQ, 1)

    pv = jnp.broadcast_to(pv0, (tq, tc))
    mv = jnp.zeros((tq, tc), jnp.uint32)
    score = jnp.broadcast_to(ql.astype(jnp.int32), (tq, tc))

    def step(i, carry):
        pv, mv, score = carry
        t = cct_ref[pl.ds(i, 1), :]                        # (1, TC)
        eq = jnp.zeros((tq, tc), jnp.uint32)
        for j in range(L):  # static unroll: disjoint bits, pure VPU work
            eq = eq | jnp.where(qc[:, j : j + 1] == t, jnp.uint32(1 << j), 0)
        return _myers_word_step(eq, pv, mv, score, i < cl, hibit)

    pv, mv, score = lax.fori_loop(0, L, step, (pv, mv, score))
    # empty pattern: distance is the text length
    out_ref[...] = jnp.where(
        ql == 0, jnp.broadcast_to(cl.astype(jnp.int32), (tq, tc)), score
    )


@functools.partial(
    jax.jit, static_argnames=("tile_q", "tile_c", "interpret")
)
def _myers_tiles_padded(qc, ql2, cct, cl2, *, tile_q, tile_c, interpret):
    qp, l = qc.shape
    cp = cct.shape[1]
    grid = (qp // tile_q, cp // tile_c)
    kernel = functools.partial(_myers_tile_kernel, L=l)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((qp, cp), jnp.int32),
        grid=grid,
        in_specs=_pair_tile_specs(l, l, tile_q, tile_c),
        out_specs=pl.BlockSpec(
            (tile_q, tile_c), lambda i, j: (i, j), memory_space=_VMEM
        ),
        interpret=interpret,
    )(qc, ql2, cct, cl2)


# Longest pattern the tiled Myers kernels cover (uint32 words unroll
# statically; beyond this the scan-DP fallback takes over).  8 words =
# 256 chars comfortably covers long text properties (addresses, titles).
MYERS_MAX_CHARS = 256


def myers_distance_tiles(qchars, qlen, cchars, clen, *, interpret=None):
    """All-pairs Levenshtein distance d(query_i, corpus_j) -> (Q, C) int32.

    qchars: (Q, L) int32 codepoints (0-padded), L <= MYERS_MAX_CHARS;
    qlen: (Q,) int32; cchars: (C, L) int32; clen: (C,) int32

    L <= 32 runs the one-word kernel; longer patterns the N-word Hyyro
    variant (explicit carry propagation, N = ceil(L/32) <= 8) — so 64-char
    default configs AND long-text schemas (128/256 chars) stay on the
    Pallas path instead of the ~600x slower scan-DP fallback.  Pads Q up
    to a sublane multiple and C up to a lane multiple; padded rows compute
    garbage distances that callers mask via their validity bits.
    """
    q = qchars.shape[0]
    c = cchars.shape[0]
    l = qchars.shape[1]
    if l > MYERS_MAX_CHARS:
        raise ValueError(
            f"Myers pallas kernels need L <= {MYERS_MAX_CHARS}, got {l}"
        )
    if interpret is None:
        interpret = _interpret()
    words = -(-l // 32)
    # lane tiles shrink as the per-pair DP state (O(W) uint32 words) grows,
    # keeping the live VMEM footprint roughly constant
    tile_c_cap = 512 if words == 1 else (256 if words <= 4 else 128)
    qc, ql2, cct, cl2, tile_q, tile_c = _stage_pair_operands(
        qchars, qlen, cchars, clen,
        tile_q_cap=128, tile_c_cap=tile_c_cap,
    )
    if words == 1:
        out = _myers_tiles_padded(
            qc, ql2, cct, cl2, tile_q=tile_q, tile_c=tile_c,
            interpret=interpret,
        )
    else:
        out = _myersN_tiles_padded(
            qc, ql2, cct, cl2, tile_q=tile_q, tile_c=tile_c,
            interpret=interpret, words=words,
        )
    return out[:q, :c]


def _carry_out(a: jnp.ndarray, b: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """Carry out of the uint32 addition s = a + b, as 0/1 uint32.

    Bitwise majority of the sign bits — Mosaic has no unsigned vector
    compare, so overflow is detected without one.
    """
    return ((a & b) | ((a ^ b) & ~s)) >> jnp.uint32(31)


def _myersN_tile_kernel(qc_ref, ql_ref, cct_ref, cl_ref, out_ref, *,
                        L: int, W: int):
    """N-word Myers/Hyyro tile: pattern lengths up to ``32 * W`` chars.

    Same layout contract as ``_myers_tile_kernel``; the bit-parallel DP
    state (Pv/Mv) spans ``W`` 32-bit words with explicit carry propagation
    through the add chain and the horizontal shifts (Hyyro's block
    formulation generalized from the round-2 two-word kernel).  The word
    lists unroll statically, so the Mosaic program grows O(W) per text
    step while all O(TQ * TC * W) bit-parallel work stays on the VPU.
    """
    tq = qc_ref.shape[0]
    tc = cct_ref.shape[1]
    qc = qc_ref[...]                      # (TQ, L)
    ql = ql_ref[...][:, :1]               # (TQ, 1)
    cl = cl_ref[...][:1, :]               # (1, TC)

    one = jnp.uint32(1)
    full = jnp.uint32(0xFFFFFFFF)

    def bits_below(n):  # (1 << n) - 1 for n in [0, 32]
        nn = jnp.clip(n, 0, 32)
        return jnp.where(nn >= 32, full,
                         (one << nn.astype(jnp.uint32)) - one)

    pv = [
        jnp.broadcast_to(bits_below(ql - 32 * w), (tq, tc)) for w in range(W)
    ]
    mv = [jnp.zeros((tq, tc), jnp.uint32) for _ in range(W)]
    # the score bit rides in the pattern's last word/bit, per query
    hi_word = (jnp.maximum(ql, 1) - 1) // 32        # (TQ, 1)
    hibit = one << ((jnp.maximum(ql, 1) - 1) % 32).astype(jnp.uint32)
    score = jnp.broadcast_to(ql.astype(jnp.int32), (tq, tc))

    def step(i, carry):
        pv = list(carry[0:W])
        mv = list(carry[W:2 * W])
        score = carry[2 * W]
        t = cct_ref[pl.ds(i, 1), :]                       # (1, TC)
        eq = []
        for w in range(W):
            e = jnp.zeros((tq, tc), jnp.uint32)
            for j in range(32 * w, min(32 * (w + 1), L)):
                e = e | jnp.where(
                    qc[:, j : j + 1] == t, jnp.uint32(1 << (j - 32 * w)), 0
                )
            eq.append(e)
        xv = [eq[w] | mv[w] for w in range(W)]
        # xh = (((eq & pv) + pv) ^ pv) | eq with a carry chain across the
        # words (the carry out of the last word falls off the pattern
        # window)
        xh = []
        c = None
        for w in range(W):
            a = eq[w] & pv[w]
            s = a + pv[w]
            cout = _carry_out(a, pv[w], s)
            if c is not None:
                s2 = s + c
                cout = cout | _carry_out(s, c, s2)
                s = s2
            xh.append((s ^ pv[w]) | eq[w])
            c = cout
        ph = [mv[w] | ~(xh[w] | pv[w]) for w in range(W)]
        mh = [pv[w] & xh[w] for w in range(W)]

        active = i < cl                                   # (1, TC)
        ph_hi, mh_hi = ph[0], mh[0]
        for w in range(1, W):
            sel = hi_word == w
            ph_hi = jnp.where(sel, ph[w], ph_hi)
            mh_hi = jnp.where(sel, mh[w], mh_hi)
        score = score + jnp.where(active & ((ph_hi & hibit) != 0), 1, 0)
        score = score - jnp.where(active & ((mh_hi & hibit) != 0), 1, 0)

        # horizontal shifts with cross-word carries
        ph_c = [p >> jnp.uint32(31) for p in ph]
        mh_c = [m >> jnp.uint32(31) for m in mh]
        nph = [(ph[0] << one) | one] + [
            (ph[w] << one) | ph_c[w - 1] for w in range(1, W)
        ]
        nmh = [mh[0] << one] + [
            (mh[w] << one) | mh_c[w - 1] for w in range(1, W)
        ]
        pv = [
            jnp.where(active, nmh[w] | ~(xv[w] | nph[w]), pv[w])
            for w in range(W)
        ]
        mv = [jnp.where(active, nph[w] & xv[w], mv[w]) for w in range(W)]
        return (*pv, *mv, score)

    out = lax.fori_loop(0, L, step, (*pv, *mv, score))
    score = out[2 * W]
    out_ref[...] = jnp.where(
        ql == 0, jnp.broadcast_to(cl.astype(jnp.int32), (tq, tc)), score
    )


@functools.partial(
    jax.jit, static_argnames=("tile_q", "tile_c", "interpret", "words")
)
def _myersN_tiles_padded(qc, ql2, cct, cl2, *, tile_q, tile_c, interpret,
                         words):
    qp, l = qc.shape
    cp = cct.shape[1]
    grid = (qp // tile_q, cp // tile_c)
    kernel = functools.partial(_myersN_tile_kernel, L=l, W=words)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((qp, cp), jnp.int32),
        grid=grid,
        in_specs=_pair_tile_specs(l, l, tile_q, tile_c),
        out_specs=pl.BlockSpec(
            (tile_q, tile_c), lambda i, j: (i, j), memory_space=_VMEM
        ),
        interpret=interpret,
    )(qc, ql2, cct, cl2)


# -- Jaro-Winkler, tiled over the pair matrix --------------------------------


def _jw_tile_kernel(qc_ref, ql_ref, cct_ref, cl_ref, out_ref, *,
                    L: int, prefix_scale: float, boost_threshold: float,
                    max_prefix: int):
    """One (TQ, TC) Jaro similarity tile (Winkler boost applied here too).

    Matched positions live in uint32 bitmasks (L <= 32): the greedy
    matching pass sets, for each query char, the lowest available bit of
    the candidate window; the transposition pass walks both masks in
    lowest-bit order extracting chars through one-hot dot products.
    Parity oracle: core.comparators._jaro / JaroWinkler (tests).
    """
    tq = qc_ref.shape[0]
    tc = cct_ref.shape[1]
    qc = qc_ref[...]                                  # (TQ, L)
    ql = ql_ref[...][:, :1].astype(jnp.int32)         # (TQ, 1)
    cl = cl_ref[...][:1, :].astype(jnp.int32)         # (1, TC)

    one = jnp.uint32(1)
    full = jnp.uint32(0xFFFFFFFF)
    l1 = jnp.broadcast_to(ql, (tq, tc))
    l2 = jnp.broadcast_to(cl, (tq, tc))
    window = jnp.maximum(jnp.maximum(l1, l2) // 2 - 1, 0)

    def bits_below(n):
        # (1 << n) - 1 with n in [0, 32]
        nn = jnp.clip(n, 0, 32)
        return jnp.where(
            nn >= 32, full, (one << nn.astype(jnp.uint32)) - one
        )

    m1 = jnp.zeros((tq, tc), jnp.uint32)
    m2 = jnp.zeros((tq, tc), jnp.uint32)
    matches = jnp.zeros((tq, tc), jnp.int32)

    for i in range(L):  # static: greedy matching, all pairs in lockstep
        ci = qc[:, i : i + 1]                         # (TQ, 1)
        eq = jnp.zeros((tq, tc), jnp.uint32)
        for j in range(L):
            eq = eq | jnp.where(
                cct_ref[j : j + 1, :] == ci, jnp.uint32(1 << j), 0
            )
        lo = jnp.maximum(i - window, 0)
        hi = jnp.minimum(l2, i + window + 1)
        wmask = bits_below(hi) & ~bits_below(lo)
        active = i < l1
        avail = eq & wmask & ~m2
        avail = jnp.where(active, avail, jnp.uint32(0))
        j_star = avail & (jnp.uint32(0) - avail)      # lowest set bit
        found = j_star != 0
        m2 = m2 | j_star
        m1 = m1 | jnp.where(found, jnp.uint32(1 << i), 0)
        matches = matches + found.astype(jnp.int32)

    # transposition pass: walk both masks lowest-bit-first, compare the
    # k-th matched chars; char extraction via one-hot dot over positions
    m1r, m2r = m1, m2
    trans = jnp.zeros((tq, tc), jnp.int32)
    for _ in range(L):
        a = m1r & (jnp.uint32(0) - m1r)
        b = m2r & (jnp.uint32(0) - m2r)
        m1r = m1r ^ a
        m2r = m2r ^ b
        ca = jnp.zeros((tq, tc), jnp.int32)
        cb = jnp.zeros((tq, tc), jnp.int32)
        for i in range(L):
            bit = jnp.uint32(1 << i)
            ca = ca + jnp.where((a & bit) != 0, qc[:, i : i + 1], 0)
            cb = cb + jnp.where((b & bit) != 0, cct_ref[i : i + 1, :], 0)
        trans = trans + ((a != 0) & (ca != cb)).astype(jnp.int32)

    m = matches.astype(jnp.float32)
    l1f = l1.astype(jnp.float32)
    l2f = l2.astype(jnp.float32)
    half_trans = (trans // 2).astype(jnp.float32)
    jaro = (m / jnp.maximum(l1f, 1.0) + m / jnp.maximum(l2f, 1.0)
            + (m - half_trans) / jnp.maximum(m, 1.0)) / 3.0
    jaro = jnp.where((matches == 0) | (l1 == 0) | (l2 == 0), 0.0, jaro)

    # Winkler common-prefix boost (max_prefix static, typically 4)
    prefix = jnp.zeros((tq, tc), jnp.int32)
    still = jnp.ones((tq, tc), jnp.bool_)
    for i in range(min(L, max_prefix)):
        ok = ((qc[:, i : i + 1] == cct_ref[i : i + 1, :])
              & (i < jnp.minimum(l1, l2)))
        still = still & ok
        prefix = prefix + still.astype(jnp.int32)
    boosted = jaro + prefix.astype(jnp.float32) * jnp.float32(
        prefix_scale
    ) * (1.0 - jaro)
    out_ref[...] = jnp.where(
        jaro < jnp.float32(boost_threshold), jaro, boosted
    )


@functools.partial(
    jax.jit,
    static_argnames=("tile_q", "tile_c", "interpret", "prefix_scale",
                     "boost_threshold", "max_prefix"),
)
def _jw_tiles_padded(qc, ql2, cct, cl2, *, tile_q, tile_c, interpret,
                     prefix_scale, boost_threshold, max_prefix):
    qp, l = qc.shape
    cp = cct.shape[1]
    grid = (qp // tile_q, cp // tile_c)
    kernel = functools.partial(
        _jw_tile_kernel, L=l, prefix_scale=prefix_scale,
        boost_threshold=boost_threshold, max_prefix=max_prefix,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((qp, cp), jnp.float32),
        grid=grid,
        in_specs=_pair_tile_specs(l, l, tile_q, tile_c),
        out_specs=pl.BlockSpec(
            (tile_q, tile_c), lambda i, j: (i, j), memory_space=_VMEM
        ),
        interpret=interpret,
    )(qc, ql2, cct, cl2)


def jaro_winkler_sim_tiles(qchars, qlen, cchars, clen, equal, *,
                           prefix_scale=0.1, boost_threshold=0.7,
                           max_prefix=4, interpret=None):
    """All-pairs Jaro-Winkler similarity -> (Q, C) f32.

    Same layout contract as ``myers_distance_tiles``; ``equal`` is the
    (Q, C) exact-equality mask (comparator's v1==v2 early exit -> 1.0).
    """
    q = qchars.shape[0]
    c = cchars.shape[0]
    if qchars.shape[1] > 32:
        raise ValueError(
            f"JW pallas kernel needs L <= 32, got {qchars.shape[1]}"
        )
    if interpret is None:
        interpret = _interpret()
    # smaller tiles than Myers: the static unrolls are O(L^2), so keep the
    # program size and VMEM live range in check
    qc, ql2, cct, cl2, tile_q, tile_c = _stage_pair_operands(
        qchars, qlen, cchars, clen, tile_q_cap=64, tile_c_cap=256
    )
    out = _jw_tiles_padded(
        qc, ql2, cct, cl2, tile_q=tile_q, tile_c=tile_c,
        interpret=interpret, prefix_scale=float(prefix_scale),
        boost_threshold=float(boost_threshold), max_prefix=int(max_prefix),
    )[:q, :c]
    return jnp.where(equal, 1.0, out)


# -- gathered-candidate Myers (ANN rescoring layout) -------------------------


def _myers_gathered_kernel(qc_ref, ql_ref, cclt_ref, cl_ref, out_ref, *,
                           L: int):
    """Per-query gathered-candidate Levenshtein tile.

    Unlike the cross-product tiles, candidate c of query q here is a
    SPECIFIC gathered corpus row (the ANN rescoring layout): text chars
    differ per (q, c) pair, so the candidate axis rides the lanes and the
    bit-parallel DP state is (TQ, TC) with per-pair text.

    qc_ref:   (TQ, L)      query codepoints (pattern)
    ql_ref:   (TQ, 1)      query lengths
    cclt_ref: (TQ, L, TC)  candidate codepoints, char axis in sublanes
    cl_ref:   (TQ, TC)     candidate lengths
    out_ref:  (TQ, TC)     int32 distances
    """
    tq = qc_ref.shape[0]
    tc = cl_ref.shape[1]
    qc = qc_ref[...]                      # (TQ, L)
    ql = ql_ref[...][:, :1]               # (TQ, 1)
    cl = cl_ref[...]                      # (TQ, TC)

    pv0, hibit = _myers_word_init(ql)     # (TQ, 1)

    pv = jnp.broadcast_to(pv0, (tq, tc))
    mv = jnp.zeros((tq, tc), jnp.uint32)
    score = jnp.broadcast_to(ql.astype(jnp.int32), (tq, tc))

    def step(i, carry):
        pv, mv, score = carry
        t = cclt_ref[:, pl.ds(i, 1), :].reshape(tq, tc)   # (TQ, TC)
        eq = jnp.zeros((tq, tc), jnp.uint32)
        for j in range(L):
            eq = eq | jnp.where(qc[:, j : j + 1] == t, jnp.uint32(1 << j), 0)
        return _myers_word_step(eq, pv, mv, score, i < cl, hibit)

    pv, mv, score = lax.fori_loop(0, L, step, (pv, mv, score))
    out_ref[...] = jnp.where(
        ql == 0, cl.astype(jnp.int32), score
    )


@functools.partial(
    jax.jit, static_argnames=("tile_q", "tile_c", "interpret")
)
def _myers_gathered_padded(qc, ql2, cclt, cl2, *, tile_q, tile_c, interpret):
    qp, l = qc.shape
    cp = cclt.shape[2]
    grid = (qp // tile_q, cp // tile_c)
    kernel = functools.partial(_myers_gathered_kernel, L=l)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((qp, cp), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_q, l), lambda i, j: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((tile_q, 1), lambda i, j: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((tile_q, l, tile_c), lambda i, j: (i, 0, j),
                         memory_space=_VMEM),
            pl.BlockSpec((tile_q, tile_c), lambda i, j: (i, j),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile_q, tile_c), lambda i, j: (i, j), memory_space=_VMEM
        ),
        interpret=interpret,
    )(qc, ql2, cclt, cl2)


def myers_distance_gathered(qchars, qlen, cchars, clen, *, interpret=None):
    """Levenshtein distance for gathered candidates -> (Q, C) int32.

    qchars: (Q, L) int32, L <= 32; qlen: (Q,)
    cchars: (Q, C, L) int32 — candidate c of query q; clen: (Q, C)
    """
    q, l = qchars.shape
    c = cchars.shape[1]
    if l > 32:
        raise ValueError(f"gathered Myers kernel needs L <= 32, got {l}")
    if interpret is None:
        interpret = _interpret()
    tile_q = min(64, _round_up(max(q, 1), 8))
    tile_c = 128  # candidate axis always pads to (at least) one full lane
    qp = _round_up(max(q, 1), tile_q)
    cp = _round_up(max(c, 1), tile_c)
    qc = jnp.zeros((qp, l), jnp.int32).at[:q].set(qchars)
    ql2 = jnp.zeros((qp, 1), jnp.int32).at[:q, 0].set(qlen)
    cclt = jnp.zeros((qp, l, cp), jnp.int32).at[:q, :, :c].set(
        jnp.transpose(cchars, (0, 2, 1))
    )
    cl2 = jnp.zeros((qp, cp), jnp.int32).at[:q, :c].set(clen)
    out = _myers_gathered_padded(
        qc, ql2, cclt, cl2, tile_q=tile_q, tile_c=tile_c, interpret=interpret
    )
    return out[:q, :c]


def levenshtein_sim_gathered(qchars, qlen, cchars, clen, equal, *,
                             interpret=None):
    """Duke Levenshtein similarity for gathered candidates: (Q, C) f32."""
    from .pairwise import levenshtein_sim_from_distance

    dist = myers_distance_gathered(
        qchars, qlen, cchars, clen, interpret=interpret
    )
    return levenshtein_sim_from_distance(
        dist, qlen[:, None], clen, equal
    )


# -- set intersection (q-grams / token sets), tiled --------------------------


def _intersect_tile_kernel(qg_ref, qn_ref, cgt_ref, cn_ref, out_ref, *, G: int):
    """One (TQ, TC) intersection-count tile.

    qg_ref:  (TQ, G)  query gram/token hashes (SET_PAD-padded)
    qn_ref:  (TQ, 1)  query set sizes
    cgt_ref: (G, TC)  corpus hashes, transposed
    cn_ref:  (1, TC)  corpus set sizes
    out_ref: (TQ, TC) int32 |A ∩ B|
    """
    tq = qg_ref.shape[0]
    tc = cgt_ref.shape[1]
    qn = qn_ref[...][:, :1]                          # (TQ, 1)
    cn = cn_ref[...][:1, :]                          # (1, TC)
    qg = qg_ref[...]                                 # (TQ, G)
    lane = lax.broadcasted_iota(jnp.int32, (tq, G), 1)

    # outer loop over query grams is a fori_loop so the program stays O(G)
    # (a static G x G unroll produced 4096-step Mosaic programs at the
    # default DEVICE_MAX_GRAMS=64); Mosaic cannot dynamic-slice the lane
    # axis, so the query column is extracted by a masked lane reduction.
    # The inner corpus loop unrolls statically: sublane slices are static
    # and every step is one (TQ, TC) vector compare on the VPU.
    def step(i, count):
        qv = jnp.sum(
            jnp.where(lane == i, qg, 0), axis=1, keepdims=True
        )                                            # (TQ, 1)
        hit = jnp.zeros((tq, tc), jnp.bool_)
        for j in range(G):
            jvalid = j < cn                          # (1, TC)
            hit = hit | ((qv == cgt_ref[j : j + 1, :]) & jvalid)
        # sets are distinct: each query element matches at most one corpus
        # element, so OR-then-add counts the intersection exactly
        return count + jnp.where(hit & (i < qn), 1, 0)

    out_ref[...] = lax.fori_loop(
        0, G, step, jnp.zeros((tq, tc), jnp.int32)
    )


@functools.partial(
    jax.jit, static_argnames=("tile_q", "tile_c", "interpret")
)
def _intersect_tiles_padded(qg, qn2, cgt, cn2, *, tile_q, tile_c, interpret):
    qp, g = qg.shape
    cp = cgt.shape[1]
    grid = (qp // tile_q, cp // tile_c)
    kernel = functools.partial(_intersect_tile_kernel, G=g)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((qp, cp), jnp.int32),
        grid=grid,
        in_specs=_pair_tile_specs(g, g, tile_q, tile_c),
        out_specs=pl.BlockSpec(
            (tile_q, tile_c), lambda i, j: (i, j), memory_space=_VMEM
        ),
        interpret=interpret,
    )(qg, qn2, cgt, cn2)


def set_intersection_tiles(qgrams, qn, cgrams, cn, *, interpret=None):
    """All-pairs |set_i ∩ set_j| -> (Q, C) int32.

    qgrams: (Q, G) int32 hashed ids (SET_PAD-padded); qn: (Q,) set sizes
    cgrams: (C, G) int32; cn: (C,) — same layout as ops.features GRAM_SET /
    TOKEN_SET tensors.  Padded rows compute garbage counts that callers
    mask via validity bits.
    """
    q, g = qgrams.shape
    c = cgrams.shape[0]
    if interpret is None:
        interpret = _interpret()
    qg, qn2, cgt, cn2, tile_q, tile_c = _stage_pair_operands(
        qgrams, qn, cgrams, cn, tile_q_cap=128, tile_c_cap=512
    )
    out = _intersect_tiles_padded(
        qg, qn2, cgt, cn2, tile_q=tile_q, tile_c=tile_c, interpret=interpret
    )
    return out[:q, :c]


def set_sim_tiles(qids, qn, cids, cn, equal, *, formula,
                  interpret=None):
    """Set-comparator similarity over all query x corpus pairs: (Q, C) f32.

    One tile entry point for QGram (``formula`` = its configured formula),
    JaccardIndex ('jaccard'), and DiceCoefficient ('dice'); the
    intersection -> similarity math is the shared
    ``ops.pairwise.sim_from_set_intersection``, so the tile and flat paths
    cannot drift.
    """
    from .pairwise import sim_from_set_intersection

    common = set_intersection_tiles(qids, qn, cids, cn, interpret=interpret)
    return sim_from_set_intersection(
        common, qn[:, None], cn[None, :], equal, formula=formula
    )


def levenshtein_sim_tiles(qchars, qlen, cchars, clen, equal, *, interpret=None):
    """Duke Levenshtein similarity over all query x corpus pairs: (Q, C) f32.

    Mirrors ops.pairwise.levenshtein_sim (core.comparators.Levenshtein
    semantics) on tiled pair distances; ``equal`` is the (Q, C) exact
    string-equality mask (from value hashes).
    """
    from .pairwise import levenshtein_sim_from_distance

    dist = myers_distance_tiles(qchars, qlen, cchars, clen, interpret=interpret)
    return levenshtein_sim_from_distance(dist, qlen[:, None], clen[None, :], equal)


# -- fused ANN retrieval: matmul + mask + segment-max in VMEM ----------------
#
# The XLA retrieval scan materializes a (Q, chunk) f32 similarity tile in
# HBM every step just so a top-C merge can read it back — at 10M rows and
# Q=1024 that is ~40 GB of traffic for a 5 GB corpus, which is why the r4
# scan measured ~0.4% MFU (VERDICT r4).  This kernel fuses the cosine
# matmul, the candidate mask, and a segment-max reduction into one VMEM
# pass: per (TC x Q) tile the scores live only on-chip, and what reaches
# HBM is the (TC/SEG, Q) per-segment running maxima + argmaxima — a SEG-x
# reduction of the write traffic.  The final top-C then runs over the
# (Q, rows/SEG) segment winners (ops.encoder.retrieval_scan), which is
# SEG-x cheaper than sorting raw similarities.  Semantically this is the
# first phase of lax.approx_max_k's PartialReduce (Chern et al. 2022) with
# the bin layout chosen to match the corpus tiling — recall loss is the
# same birthday-collision bound, configured via DEVICE_ANN_SEG.
#
# Layout: scores are computed TRANSPOSED — (TC corpus rows, Q queries) —
# so the segment reduction runs over sublanes (corpus axis) while queries
# ride the lanes; outputs are (rows/SEG, Q) and the caller transposes
# once (O(rows/SEG * Q) traffic, amortized SEG-x).


# Encoded candidate mask: one int8 per corpus row, broadcast across a
# 128-lane axis so the operand is tile-native — (N, 1) int32 columns get
# T(8,128)-padded 128x by XLA's custom-call layout (a 4.8 GB temp copy at
# 10M rows, measured OOM), and Mosaic cannot shape-cast a lane-major
# block back to a column, so the kernel recovers the column with a lane
# max-reduction instead.  enc = 0 dead/tombstoned, group + GROUP_OFFSET
# live; group ids in this engine are tiny (-1 for dedup, the dataset
# group numbers 1/2 for linkage — service/datasource.py), so int8 holds
# them with room to spare.
GROUP_OFFSET = 2


def _retrieval_segmax_kernel(qT_ref, c_ref, enc_ref, qrow_ref,
                             qgroupe_ref, max_ref, arg_ref, *,
                             tc: int, seg: int, group_filtering: bool,
                             neg: float):
    scores = jnp.dot(
        c_ref[...], qT_ref[...], preferred_element_type=jnp.float32
    )  # (TC, Q) on the MXU
    cidx = (pl.program_id(0) * tc
            + lax.broadcasted_iota(jnp.int32, (tc, 1), 0))
    enc = jnp.max(enc_ref[...].astype(jnp.int32), axis=1, keepdims=True)
    mask = enc > 0                                        # (TC, 1)
    if group_filtering:
        mask = mask & (enc != qgroupe_ref[...])           # (TC, Q)
    mask = mask & (cidx != qrow_ref[...])                 # self-exclusion
    scores = jnp.where(mask, scores, jnp.float32(neg))
    q = scores.shape[1]
    # STRIDED binning: row r of the tile lands in bin r mod (TC/SEG), so
    # ADJACENT corpus rows go to DIFFERENT bins.  Duplicates are adjacent
    # by construction in this workload (a batch commits into contiguous
    # rows), so contiguous binning would collapse a duplicate cluster
    # into one survivor — silently dropping matches AND starving the
    # count-saturation signal the C-escalation loop needs.  Strided bins
    # tolerate clusters up to TC/SEG rows per tile (lax.approx_max_k's
    # TPU PartialReduce is adjacency-safe the same way, verified in
    # tests/test_fused_retrieval.py); wider clusters degrade to TC/SEG
    # retrieved members, which still saturates the count signal whenever
    # C <= TC/SEG.
    s3 = scores.reshape(seg, tc // seg, q)
    seg_max = jnp.max(s3, axis=0)                         # (TC/SEG, Q)
    rid3 = cidx.reshape(seg, tc // seg, 1)
    big = jnp.int32(2**31 - 1)
    seg_arg = jnp.min(
        jnp.where(s3 == seg_max[None, :, :], rid3, big), axis=0
    )
    max_ref[...] = seg_max
    arg_ref[...] = seg_arg


@functools.partial(
    jax.jit,
    static_argnames=("tc", "seg", "group_filtering", "interpret"),
)
def retrieval_segmax(qT, corpus_emb, enc, qrow_local, qgroup_enc, *,
                     tc: int, seg: int, group_filtering: bool,
                     interpret=None):
    """Fused retrieval phase 1: per-segment (max, argmax) of masked cosine
    scores over the whole corpus.

    Operands (pre-staged by ops.encoder.retrieval_scan):
      qT          (D, Q)   bf16 — queries transposed, Q a lane multiple
      corpus_emb  (N, D)   bf16 — N a multiple of ``tc``
      enc         (N, 128) int8 — encoded mask, identical across lanes:
                  0 = dead/tombstoned, group + GROUP_OFFSET = live
      qrow_local  (1, Q)   int32 — query's own LOCAL corpus row (-1 none)
      qgroup_enc  (1, Q)   int32 — query group + GROUP_OFFSET

    Returns (seg_max (N/seg, Q) f32, seg_arg (N/seg, Q) int32) with LOCAL
    row ids; all-masked segments carry ``neg`` and an arbitrary masked
    row — the caller turns those into -1 via the value sentinel.
    """
    if interpret is None:
        interpret = _interpret()
    n, d = corpus_emb.shape
    q = qT.shape[1]
    neg = -3.0e38
    grid = (n // tc,)
    kernel = functools.partial(
        _retrieval_segmax_kernel, tc=tc, seg=seg,
        group_filtering=group_filtering, neg=neg,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((d, q), lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((tc, d), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((tc, 128), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((1, q), lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec((1, q), lambda i: (0, 0), memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n // seg, q), jnp.float32),
            jax.ShapeDtypeStruct((n // seg, q), jnp.int32),
        ],
        out_specs=[
            pl.BlockSpec((tc // seg, q), lambda i: (i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((tc // seg, q), lambda i: (i, 0),
                         memory_space=_VMEM),
        ],
        interpret=interpret,
    )(qT, corpus_emb, enc, qrow_local, qgroup_enc)
