"""The device scoring program: per-property kernels + naive-Bayes combine.

Assembles, for a given schema feature plan (ops.features.SchemaFeatures), a
jitted function that scores a block of Q query records against the whole
device-resident corpus in chunks, maintaining a running top-K per query.
This replaces the reference hot loop (candidate fetch + per-pair comparator
dispatch + Bayes fold, SURVEY.md section 3.2) with one XLA program:

    for each corpus chunk (lax.scan, static trip count):
        sims  = per-property pairwise kernels        (ops.pairwise)
        probs = Duke's [low, high] similarity map    (per property)
        logit = sum of clamped log-odds              (naive Bayes, 0.5 prior)
        merge chunk scores into running top-K        (lax.top_k)

Hybrid host properties: comparators without a device kernel contribute an
*optimistic* constant logit bound on device (max(0, logit(high)) per
property); ranking is by the device partial logit (the constant does not
reorder), and the host adds the exact contributions for the surviving top-K
pairs only — exact semantics at O(K) host work per query instead of O(N).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import comparators as C
from . import features as F
from . import pairwise as pw
from . import pallas_kernels as pk

# Sentinel for empty top-K slots (logit scale).
NEG_INF = -3.0e38

# Matches core.bayes._EPS: probabilities clamped away from {0, 1}.
_EPS = 1e-10
_MAX_LOGIT = math.log((1.0 - _EPS) / _EPS)


def probability_to_logit(p: float) -> float:
    p = min(max(p, _EPS), 1.0 - _EPS)
    return math.log(p / (1.0 - p))


def host_bound_logit(host_props) -> float:
    """Optimistic total logit the host-scored properties could contribute."""
    return sum(max(0.0, probability_to_logit(p.high)) for p in host_props)


_F32_EPS = float(np.finfo(np.float32).eps)


def certified_f32_margin(plan: "F.SchemaFeatures") -> float:
    """Certified upper bound on |device f32 logit - exact f64 logit|.

    The device program computes, per property, a similarity, Duke's
    quadratic probability map, and a clamped log-odds, then sums the
    per-property logits — all in float32.  Per property the error budget
    has two parts:

      * **similarity error through the map**: a per-kernel-kind
        similarity budget (``_SIM_ERROR_BOUND``: 64 ulps for the
        integer-count-ratio kernels, wider for weighted-Levenshtein and
        numeric, uncertifiable for THAT PROPERTY under geoposition —
        an ``inf`` entry collapses this whole-schema bound, so decisive
        pruning degrades to rescore-everything, but the device-finalize
        split in ``engine.finalize`` falls back to the host PER
        PROPERTY: the remaining certifiable properties keep their
        device verdicts), amplified by the
        worst-case slope of the probability→log-odds composition.
        ``|dlogit/dp| = 1/(p(1-p))`` and ``|dp/dsim| <= 1``, so the
        amplification is bounded by ``1/min(high(1-high), low(1-low))``
        — a property with an extreme ``high`` (sharp log-odds) correctly
        demands a wider margin;
      * **direct rounding of the log-odds**: 32 ulps of the clamp
        ``_MAX_LOGIT``.

    The final sum of ``n`` clamped terms adds ``n * ulp(n * _MAX_LOGIT)``
    of accumulation error.  Branch discontinuities (the ``sim >= 0.5``
    split in the probability map) are outside any rounding bound — they
    are the same measure-zero exposure the device-side survivor filter
    has always had and are covered by the differential tests, not by
    this margin.

    When a schema's sharp properties push this margin past the device
    filter's fixed 1e-3 insurance margin the decisive band is empty
    (the prune bound falls below the filter bound, so no survivor ever
    sits in it) — pruning degrades to "rescore everything", never to
    unsoundness.  The filter itself deliberately stays at 1e-3: a
    degenerate config (low=0.0 / high=1.0) makes this margin huge, and
    widening the filter with it would stop filtering at all.

    Used by decisive-band pruning (engine.finalize): a survivor whose
    device logit plus this margin plus the optimistic host-property bound
    still cannot reach ``logit(min_threshold)`` certifiably cannot emit an
    event, so its exact host rescore is skipped.
    """
    n = max(1, len(plan.device_props))
    total = n * _F32_EPS * (n * _MAX_LOGIT)  # accumulation of the sum
    for spec in plan.device_props:
        high = min(max(float(spec.high), _EPS), 1.0 - _EPS)
        low = min(max(float(spec.low), _EPS), 1.0 - _EPS)
        amplification = 1.0 / min(high * (1.0 - high), low * (1.0 - low))
        sim_err = _SIM_ERROR_BOUND.get(spec.kind, float("inf"))
        # a property's logit is clamped to [-_MAX_LOGIT, _MAX_LOGIT], so
        # however steep the map, its error cannot exceed the clamp range
        total += min(sim_err * amplification, 2.0 * _MAX_LOGIT)
        total += 32.0 * _F32_EPS * _MAX_LOGIT      # log-odds rounding
    return total


# Per-kind absolute similarity-error bounds for the certified margin.
# Edit-distance / set / hash / phonetic sims are ratios of exact integer
# counts with one final f32 division — 64 ulps is generous.  Weighted
# Levenshtein accumulates up to ~256 f32 weight additions; numeric is a
# ratio of f32-quantized doubles; both get wider budgets.  Geoposition is
# NOT certifiable — but only PER PROPERTY: f32 lat/lon quantization alone
# is meters of position error, arbitrarily large in similarity units for
# small max-distance.  Because this whole-schema margin takes a sum over
# properties, one inf entry still collapses the decisive band (rescore
# everything) for any schema carrying a geo property — the sound default
# for unknown future kinds too — while the per-property device-finalize
# split (``engine.finalize``, ISSUE 12) routes ONLY the geo property to
# the host and keeps certified device verdicts for the rest.
# Ledger derivations (scripts/dukecheck/budgets, docs/ERROR_BUDGETS.md):
# the ratio kinds pay one f32 division plus the quadratic map (~8 ulps
# total before amplification), weighted Levenshtein pays ~256 weight
# accumulations, numeric a ratio of f32-quantized doubles.  GEO is
# uncertifiable BY DESIGN (inf — no annotation; see the block comment).
_SIM_ERROR_BOUND = {
    F.CHARS: 64.0 * _F32_EPS,          # dd-budget: _SIM_ERROR_BOUND[CHARS] covers 8 * eps32 headroom 4
    F.GRAM_SET: 64.0 * _F32_EPS,       # dd-budget: _SIM_ERROR_BOUND[GRAM_SET] covers 8 * eps32 headroom 4
    F.TOKEN_SET: 64.0 * _F32_EPS,      # dd-budget: _SIM_ERROR_BOUND[TOKEN_SET] covers 8 * eps32 headroom 4
    F.HASH: 64.0 * _F32_EPS,           # dd-budget: _SIM_ERROR_BOUND[HASH] covers 2 * eps32 headroom 16
    F.PHONETIC: 64.0 * _F32_EPS,       # dd-budget: _SIM_ERROR_BOUND[PHONETIC] covers 2 * eps32 headroom 16
    F.CHARS_WEIGHTED: 2048.0 * _F32_EPS,  # dd-budget: _SIM_ERROR_BOUND[CHARS_WEIGHTED] covers 2 * 256 * eps32 headroom 2
    F.NUMERIC: 256.0 * _F32_EPS,       # dd-budget: _SIM_ERROR_BOUND[NUMERIC] covers 64 * eps32 headroom 2
    F.GEO: float("inf"),
}


def emit_bound_logit(schema, plan: "F.SchemaFeatures",
                     margin: float) -> float:
    """ONE copy of the survivor-bound formula: the device logit below
    which a pair cannot emit an event at the given error ``margin`` —
    ``logit(min(threshold, maybe_threshold))`` minus the optimistic
    host-property contribution minus ``margin``.  The device-side
    survivor filter and decisive-band pruning both derive from this, so
    they can never drift onto different threshold/host-bound handling
    (pruning soundness requires the prune bound to sit inside the
    filter's retained band)."""
    thresholds = [schema.threshold]
    if schema.maybe_threshold:
        thresholds.append(schema.maybe_threshold)
    return (
        probability_to_logit(min(thresholds))
        - host_bound_logit(plan.host_props)
        - margin
    )


def decisive_prune_logit(schema, plan: "F.SchemaFeatures") -> float:
    """Device-logit bound below which a survivor is *decisively* a
    non-event: ``device_logit <= decisive_prune_logit`` implies the exact
    f64 pair probability cannot exceed ``min(threshold, maybe_threshold)``
    even with every host-scored property at its optimistic maximum and the
    certified float32 error credited in the survivor's favor.  Survivors
    at or below this bound skip the host ``compare`` call entirely;
    everything above it is rescored host-exact, so emitted probabilities
    stay bit-identical to the host engine."""
    return emit_bound_logit(schema, plan, certified_f32_margin(plan))


# -- certified double-double (emulated-f64) finalization ---------------------
#
# ISSUE 12 tentpole.  The f32 margin above is a PRUNING bound: sharp
# schemas amplify 64 float32 ulps into a band wide enough that most
# survivors still need the host's exact f64 ``compare``.  The dd rescore
# re-runs the comparator->probability->log-odds pipeline for the
# surviving top-K pairs in two-float (~49-bit) arithmetic (ops.dd): the
# integer counts the comparators reduce to (edit distances, set
# intersection sizes, match/transposition counts, lengths) are already
# exact on device, so only the final ratio, Duke's quadratic probability
# map, and the clamped Bayes logit sum need the extended precision.  The
# resulting per-pair dd logit is within ``certified_dd_margin`` —
# typically ~1e-10 logit units — of the host's f64 value, so a verdict
# whose logit sits farther than the margin from every decision boundary
# is *bit-certified*: the host compare provably classifies it the same
# way, and a certified reject can skip the host entirely.
#
# Branch-discontinuity soundness: every branch predicate in the
# certified family compares a rational of BOUNDED INTEGERS against a
# constant.  For the single-division kinds (Levenshtein, sets) the
# argument is spacing: a rational a/b differs from a non-equal constant
# p/q by at least 1/(qb) — >= ~1e-7 at the width caps, five orders above
# the dd evaluation error — and when the exact ratio EQUALS the constant
# the division is exact in both f64 and dd (dyadic results round clean),
# so both sides take the same branch.  Jaro-Winkler is different: its
# ``j`` is a SUM of three ratios, so an exactly-attainable boundary value
# (j == 1/2 or 7/10 — e.g. (1/3 + 1/2 + 2/3)/3 == 0.5 exactly) is
# computed INEXACTLY by both the host f64 chain and the dd chain, and
# the two roundings can land on opposite sides of the comparison
# (observed in the randomized differential: host j == 0.5 took the map's
# high branch, dd j == 0.5 - 2^-45 took ``low`` — a 1.17-logit verdict
# flip).  JW pairs whose dd ``j`` sits within ``_DD_JW_BRANCH_GUARD`` of
# a branch constant are therefore flagged into the host residue; off the
# guard band, |host j - dd j| <= ~1e-12 << guard keeps the branches
# aligned.  Hash-collision exposure (``equal`` and gram/token ids ride
# 64/32-bit FNV hashes) is exactly the f32 certified path's existing
# featurization assumption — and a false-positive ``equal`` only RAISES
# the dd logit, pushing the pair toward host rescore, never toward a
# wrong certified reject.

def _dd():
    from . import dd as D

    return D


# Feature kinds whose device counts are exact integers — the certified
# dd family.  CHARS_WEIGHTED (f32 weight accumulation), NUMERIC (inputs
# f32-quantized at extraction) and GEO (uncertifiable per the f32 table)
# fall back to the host per property.
DD_KINDS = (F.CHARS, F.GRAM_SET, F.TOKEN_SET, F.HASH, F.PHONETIC)

# Kinds that deliberately take the per-property host fallback instead of
# a certified dd kernel.  DECLARATIVE, and machine-checked: dukecheck's
# numerics gate (DK604) asserts DD_KINDS and DD_FALLBACK_KINDS partition
# ``ops.features.ALL_KINDS`` exactly, and that every dd kind carries a
# ``_DD_SIM_OPS`` budget and every kind a ``_SIM_ERROR_BOUND`` entry —
# a future comparator kind cannot silently ship without a reviewed
# margin entry or an explicit fallback decision.
DD_FALLBACK_KINDS = (F.CHARS_WEIGHTED, F.NUMERIC, F.GEO)

# Jaro-Winkler's branch constants (boost 0.7, the 0.5 map split) are
# compared against rationals with denominator 3*n1*n2*m; past this char
# width the rational spacing argument above thins below 1e-7, so wider
# JW properties fall back to the host instead of eroding the proof.
_DD_JW_MAX_CHARS = 64

# dd similarity-error budgets, in units absorbed by certified_dd_margin:
# ratio kinds pay one dd division + the map's ~6 dd ops; JW pays three
# divisions, the 3-term average and the boost; hash/phonetic are
# constants reproduced from the oracle's own f64 values.  All generous
# multiples of the per-op DD_EPS.
# (ledger: ratio kinds pay one dd division + ~2 fold ops + the ~6-op
# map; JW pays three divisions, the 3-term average, the prefix boost and
# the map, with every term of magnitude <= 2; hash/phonetic reproduce
# oracle constants through the map alone.)
_DD_SIM_OPS = {
    F.CHARS: 64.0,      # dd-budget: _DD_SIM_OPS[CHARS] covers 12 headroom 4
    F.GRAM_SET: 64.0,   # dd-budget: _DD_SIM_OPS[GRAM_SET] covers 14 headroom 4
    F.TOKEN_SET: 64.0,  # dd-budget: _DD_SIM_OPS[TOKEN_SET] covers 14 headroom 4
    F.HASH: 16.0,       # dd-budget: _DD_SIM_OPS[HASH] covers 4 headroom 2
    F.PHONETIC: 16.0,   # dd-budget: _DD_SIM_OPS[PHONETIC] covers 4 headroom 2
}
# dd-budget: _DD_JW_SIM_OPS covers 2 * 22 headroom 4
_DD_JW_SIM_OPS = 256.0


def dd_certifiable_spec(spec: "F.PropertyFeatureSpec") -> bool:
    """Can this device property's verdict ride the certified dd rescore?

    Kind must be in the integer-count-ratio family; Jaro-Winkler
    additionally caps the char width (see ``_DD_JW_MAX_CHARS``).
    """
    if spec.kind not in DD_KINDS:
        return False
    if spec.kind == F.CHARS and isinstance(spec.comparator, C.JaroWinkler):
        return spec.chars <= _DD_JW_MAX_CHARS
    return True


def dd_plan_specs(plan: "F.SchemaFeatures"):
    """The dd-certifiable subset of the plan's device properties."""
    return [s for s in plan.device_props if dd_certifiable_spec(s)]


def dd_fallback_props(schema, plan: "F.SchemaFeatures"):
    """Properties the device-finalize path evaluates on host PER PAIR:
    the plan's host-only properties plus device properties whose kind is
    not dd-certifiable (weighted-lev / numeric / geo — the per-property
    fallback, not a per-schema collapse).  Returns core Property objects
    in schema order so the host-side fold matches the oracle's."""
    dd_names = {s.name for s in dd_plan_specs(plan)}
    return [p for p in schema.comparison_properties()
            if p.name not in dd_names]


def certified_dd_margin(plan: "F.SchemaFeatures") -> float:
    """Certified bound on |dd device logit - host f64 logit| for the
    dd-certifiable properties of ``plan``.

    Sibling of ``certified_f32_margin`` with the same structure — a
    per-property similarity budget amplified by the worst-case
    probability->log-odds slope, a per-property log-evaluation budget,
    and a sum-accumulation term — but charged at the dd per-op epsilon
    (``ops.dd.DD_EPS`` = 2^-44, itself generous against the ~2^-47 true
    double-float bounds) instead of float32 ulps.  The slack also
    absorbs the HOST side's own f64 rounding (u64 = 2^-53 per op,
    hundreds of times below DD_EPS), so the bound is against the host's
    computed value, not the exact real — which is what verdict
    certification needs.  Typical schemas land near 1e-10 logit units,
    ~7 orders of magnitude inside the f32 margin; even a degenerate
    high=1-1e-8 property (amplification 1e8) keeps the dd band at
    ~1e-3, where the f32 band has long since collapsed.

    Only dd-certifiable properties contribute: the uncertifiable kinds
    are evaluated on host per property (``dd_fallback_props``), exactly,
    so they add f64 noise covered by the accumulation term, never an
    amplified similarity error.
    """
    D = _dd()
    specs = dd_plan_specs(plan)
    n_all = max(1, len(plan.device_props) + len(plan.host_props))
    # f64 accumulation-order slack: the oracle interleaves dd and host
    # properties in schema order, the split path sums them in two runs
    total = n_all * D.DD_EPS * (n_all * _MAX_LOGIT)
    for spec in specs:
        high = min(max(float(spec.high), _EPS), 1.0 - _EPS)
        low = min(max(float(spec.low), _EPS), 1.0 - _EPS)
        amplification = 1.0 / min(high * (1.0 - high), low * (1.0 - low))
        if spec.kind == F.CHARS and isinstance(spec.comparator,
                                               C.JaroWinkler):
            sim_err = _DD_JW_SIM_OPS * D.DD_EPS
        else:
            sim_err = _DD_SIM_OPS[spec.kind] * D.DD_EPS
        total += min(sim_err * amplification, 2.0 * _MAX_LOGIT)
        # dd log evaluation: absolute + relative parts (ops.dd bounds)
        total += 2.0 * (D.LOG_ERR_ABS + D.DD_EPS * _MAX_LOGIT)
    return total


def _dd_threshold_slack(threshold: float) -> float:
    """Logit-space slack covering the host's PROBABILITY-space compare.

    The oracle classifies ``sigmoid(logit) > t`` with both sides in f64;
    certification compares logits against ``probability_to_logit(t)``.
    The translation costs a few f64 ulps of the sigmoid evaluation
    amplified by the logit slope at ``t`` plus the rounding of
    ``logit(t)`` itself — generous at 64 u64 per part.
    """
    t = min(max(float(threshold), _EPS), 1.0 - _EPS)
    u64 = 2.0 ** -53
    return 64.0 * u64 * (1.0 / (t * (1.0 - t))) + 64.0 * u64 * _MAX_LOGIT


def dd_reject_bound(schema, plan: "F.SchemaFeatures") -> float:
    """Total-logit bound below which a survivor is a *certified reject*:
    ``dd_logit + exact host-property logits <= this`` implies the host
    f64 probability cannot exceed ``min(threshold, maybe_threshold)``,
    so no event is possible and the host ``compare`` is skipped.

    Unlike ``decisive_prune_logit`` there is no optimistic host-property
    bound to subtract — the fallback properties are evaluated EXACTLY on
    host per pair — so the band around the boundary is just the dd
    margin plus the probability-space comparison slack."""
    thresholds = [schema.threshold]
    if schema.maybe_threshold:
        thresholds.append(schema.maybe_threshold)
    t = min(thresholds)
    return (probability_to_logit(t) - certified_dd_margin(plan)
            - _dd_threshold_slack(t))


def dd_gate_bound(schema, plan: "F.SchemaFeatures") -> float:
    """f32-device-logit bound above which a survivor certifiably CANNOT
    be a dd certified reject — the block-level dispatch gate.

    A pair's certification total is the f64 logit over every property:
    the f32 device logit approximates the device-property part within
    ``certified_f32_margin`` (infinite for geo/degenerate schemas —
    then the gate is +inf and the dd program always dispatches, which
    is sound), and the host-only properties contribute at least
    ``sum(min(0, logit(min(low, 0.5))))`` (each is missing-neutral 0 or
    at worst its clamped ``low``).  A survivor whose f32 logit already
    exceeds ``dd_reject_bound`` plus those two allowances can only be a
    certified EVENT or residue — both take the host compare regardless
    — so a block with no survivor under this bound skips the dd rescore
    program entirely (the common shape for duplicate-heavy ingest,
    where every survivor is an emitter)."""
    lmin = 0.0
    for p in plan.host_props:
        lmin += min(0.0, probability_to_logit(min(float(p.low), 0.5)))
    return (dd_reject_bound(schema, plan) + certified_f32_margin(plan)
            - lmin)


def dd_event_bound(schema, plan: "F.SchemaFeatures") -> float:
    """Total-logit bound above which a survivor *certifiably emits* some
    event (match or maybe).  Such pairs still take one host ``compare``
    — the emitted confidence must be the bit-exact f64 value — but they
    are a certified verdict, not ambiguous residue: the host work is
    O(emitted events), not O(survivors)."""
    thresholds = [schema.threshold]
    if schema.maybe_threshold:
        thresholds.append(schema.maybe_threshold)
    t = min(thresholds)
    return (probability_to_logit(t) + certified_dd_margin(plan)
            + _dd_threshold_slack(t))


# -- the dd rescore program ---------------------------------------------------


def _dd_map_probability(spec, sim, one):
    """Duke's probability map in dd, returning (p, one_minus_p).

    ``p`` mirrors the oracle's f64 expression ``(high-0.5)*sim^2 + 0.5``
    term for term (the dd constants are splits of the very f64
    intermediates the host computes), while ``one_minus_p`` uses the
    cancellation-free rearrangement ``0.5*(1-sim^2) + (1-high)*sim^2``
    so its RELATIVE accuracy survives ``high`` near 1 — the log of the
    complement is where a naive ``1 - p`` would burn the whole margin.
    """
    D = _dd()
    like = sim[0]
    half = D.const(0.5, like=like)
    ge05 = D.ge(sim, half)
    s2 = D.mul(sim, sim)
    hc = D.const(float(spec.high) - 0.5, like=like)
    p_hi = D.add(D.mul(hc, s2), half)
    omp_hi = D.add(
        D.mul(half, D.sub(one, s2)),
        D.mul(D.const(1.0 - float(spec.high), like=like), s2),
    )
    p_lo = D.const(float(spec.low), like=like)
    omp_lo = D.const(1.0 - float(spec.low), like=like)
    return D.where(ge05, p_hi, p_lo), D.where(ge05, omp_hi, omp_lo)


def _dd_levenshtein_sim(c1, l1, c2, l2, equal, *, dist=None):
    """Levenshtein similarity in dd from the exact integer distance."""
    D = _dd()
    if dist is None:
        if c1.shape[1] <= 32:
            dist = pw.levenshtein_distance_myers(c1, l1, c2, l2)
        else:
            dist = pw.levenshtein_distance(c1, l1, c2, l2)
    shorter = jnp.minimum(l1, l2)
    longer = jnp.maximum(l1, l2)
    dist = jnp.minimum(dist, shorter)
    one = D.from_f32(jnp.ones(dist.shape, jnp.float32))
    sim = D.sub(one, D.div(D.from_int(dist),
                           D.from_int(jnp.maximum(shorter, 1))))
    zero = ((longer - shorter) * 2 > shorter) | (shorter == 0)
    sim = D.where(zero, D.const(0.0, like=sim[0]), sim)
    return D.where(equal, one, sim)


# JW branch-guard half-width (see the soundness block above): far above
# the ~1e-12 dd + f64 evaluation noise of ``j``, far below the ~1e-7
# rational spacing of non-boundary j values — pairs inside it go to the
# host residue instead of trusting a branch both sides computed
# inexactly.  Two-sided ledger check: the guard must cover the ~20-op
# dd evaluation noise of ``j`` with two orders of slack (covers), AND
# stay an order under the worst rational spacing 1/(q_max * 3 * n^3) at
# the 64-char JW width cap with boundary-constant denominator q_max=10
# (0.5 = 1/2, 0.7 = 7/10) — widening it past that would flag pairs the
# spacing proof already certifies (below).
# dd-budget: _DD_JW_BRANCH_GUARD covers 100 * 20 * DD_EPS headroom 4 below 1 / (10 * 3 * 64**3) / 8
_DD_JW_BRANCH_GUARD = 1e-9


def _dd_jaro_winkler_sim(c1, l1, c2, l2, equal, cmp):
    """Jaro-Winkler in dd from the exact match/transposition counts.

    Returns (sim, branch_unsafe): pairs whose ``j`` sits inside the
    guard band of the 0.5 map split or the boost threshold cannot be
    certified (host f64 and dd may round an exactly-boundary ``j`` to
    opposite sides) and must take the host path.
    """
    D = _dd()
    m, t = pw.jaro_counts(c1, l1, c2, l2)
    prefix = pw.common_prefix_count(c1, c2, l1, l2,
                                    max_prefix=int(cmp.max_prefix))
    md = D.from_int(m)
    a = D.div(md, D.from_int(jnp.maximum(l1, 1)))
    b = D.div(md, D.from_int(jnp.maximum(l2, 1)))
    cpart = D.div(D.from_int(m - t), D.from_int(jnp.maximum(m, 1)))
    like = a[0]
    j = D.div(D.add(D.add(a, b), cpart), D.const(3.0, like=like))
    zero = (m == 0) | (l1 == 0) | (l2 == 0)
    j = D.where(zero, D.const(0.0, like=like), j)
    one = D.from_f32(jnp.ones_like(like))
    # oracle: j + prefix * prefix_scale * (1.0 - j), left-associated
    boosted = D.add(j, D.mul(
        D.mul(D.from_int(prefix), D.const(float(cmp.prefix_scale),
                                          like=like)),
        D.sub(one, j),
    ))
    boost_c = D.const(float(cmp.boost_threshold), like=like)
    sim = D.where(D.lt(j, boost_c), j, boosted)
    # the dd sub's hi word carries the (cancellation-exact) distance to
    # the branch constants at full small-magnitude f32 resolution
    guard = jnp.float32(_DD_JW_BRANCH_GUARD)
    near_map = jnp.abs(D.sub(j, D.const(0.5, like=like))[0]) < guard
    near_boost = jnp.abs(D.sub(j, boost_c)[0]) < guard
    unsafe = (near_map | near_boost) & ~equal & ~zero
    return D.where(equal, one, sim), unsafe


def _dd_set_sim(common, f1, f2, equal, *, formula):
    """Set-overlap similarity in dd from exact intersection counts."""
    D = _dd()
    c = D.from_int(common)
    if formula == "jaccard":
        sim = D.div(c, D.from_int(jnp.maximum(f1 + f2 - common, 1)))
    elif formula == "dice":
        sim = D.div(D.from_int(2 * common),
                    D.from_int(jnp.maximum(f1 + f2, 1)))
    else:
        sim = D.div(c, D.from_int(jnp.maximum(jnp.minimum(f1, f2), 1)))
    one = D.from_f32(jnp.ones(common.shape, jnp.float32))
    sim = D.where((f1 == 0) | (f2 == 0), D.const(0.0, like=sim[0]), sim)
    return D.where(equal, one, sim)


def _dd_property_sim(spec: "F.PropertyFeatureSpec", qf, cf,
                     pallas_ok: bool):
    """(dd sim, combo_valid, branch_unsafe | None) for one certified
    property, gathered layout ((Q, Vq, ...) queries x (Q, C, Vc, ...)
    candidates), flat combos.  ``branch_unsafe`` is non-None only for
    kinds with a multi-op similarity (Jaro-Winkler) whose boundary
    values need the runtime guard band."""
    D = _dd()
    expand = _pair_expand_gathered
    hh1, hh2 = expand(qf["hash_hi"], cf["hash_hi"])
    hl1, hl2 = expand(qf["hash_lo"], cf["hash_lo"])
    v1, v2 = expand(qf["valid"], cf["valid"])
    combo_valid = v1 & v2
    equal = (hh1 == hh2) & (hl1 == hl2) & combo_valid

    kind = spec.kind
    cmp = spec.comparator
    if kind == F.CHARS and isinstance(cmp, C.JaroWinkler):
        c1, c2 = expand(qf["chars"], cf["chars"])
        l1, l2 = expand(qf["length"], cf["length"])
        sim, branch_unsafe = _dd_jaro_winkler_sim(c1, l1, c2, l2, equal,
                                                  cmp)
        return sim, combo_valid, branch_unsafe
    if kind == F.CHARS:
        if (
            pallas_ok
            and qf["chars"].shape[1] == 1      # single value slot per side
            and cf["chars"].shape[2] == 1
            and qf["chars"].shape[2] <= 32
            and pk.pallas_enabled()
        ):
            # ride the existing gathered Myers Pallas tile kernel — the
            # dd path only needs its exact integer DISTANCE, the ratio
            # and map run in dd outside the kernel
            q = qf["valid"].shape[0]
            c = cf["valid"].shape[1]
            dist = pk.myers_distance_gathered(
                qf["chars"][:, 0], qf["length"][:, 0],
                cf["chars"][:, :, 0], cf["length"][:, :, 0],
            ).reshape(-1)
            l1 = jnp.broadcast_to(
                qf["length"][:, None, 0], (q, c)).reshape(-1)
            l2 = cf["length"][:, :, 0].reshape(-1)
            return (_dd_levenshtein_sim(None, l1, None, l2, equal,
                                        dist=dist), combo_valid,
                    None)
        c1, c2 = expand(qf["chars"], cf["chars"])
        l1, l2 = expand(qf["length"], cf["length"])
        return (_dd_levenshtein_sim(c1, l1, c2, l2, equal), combo_valid,
                None)
    if kind == F.GRAM_SET:
        g1, g2 = expand(qf["grams"], cf["grams"])
        n1, n2 = expand(qf["gram_count"], cf["gram_count"])
        common = pw.set_intersection_count(g1, n1, g2, n2)
        return _dd_set_sim(common, n1, n2, equal,
                           formula=cmp.formula), combo_valid, None
    if kind == F.TOKEN_SET:
        t1, t2 = expand(qf["tokens"], cf["tokens"])
        n1, n2 = expand(qf["token_count"], cf["token_count"])
        formula = "dice" if isinstance(cmp, C.DiceCoefficient) else "jaccard"
        common = pw.set_intersection_count(t1, n1, t2, n2)
        return _dd_set_sim(common, n1, n2, equal,
                           formula=formula), combo_valid, None
    if kind == F.HASH:
        one = D.from_f32(jnp.ones(equal.shape, jnp.float32))
        zero = D.const(0.0, like=one[0])
        if isinstance(cmp, C.Different):
            return D.where(equal, zero, one), combo_valid, None
        return D.where(equal, one, zero), combo_valid, None
    if kind == F.PHONETIC:
        ch1, ch2 = expand(qf["code_hi"], cf["code_hi"])
        cl1, cl2 = expand(qf["code_lo"], cf["code_lo"])
        cv1, cv2 = expand(qf["code_valid"], cf["code_valid"])
        one = D.from_f32(jnp.ones(equal.shape, jnp.float32))
        code_eq = (ch1 == ch2) & (cl1 == cl2) & cv1 & cv2
        sim = D.where(code_eq, D.const(0.9, like=one[0]),
                      D.const(0.0, like=one[0]))
        return D.where(equal, one, sim), combo_valid, None
    raise ValueError(  # pragma: no cover - dd_certifiable_spec gates kinds
        f"no dd kernel for feature kind {kind!r}")


# The oracle's clamp rails (core.bayes.probability_logit): pairs whose
# best probability clamps reproduce the host's exact f64 logit constant.
_DD_EPS_P = 1e-10


def _dd_property_logit(spec, qf, cf, q: int, c: int, pallas_ok: bool):
    """One certified property's clamped log-odds in dd plus its
    branch-unsafety: (((Q, C) hi, lo), (Q, C) bool).

    Mirrors ``_property_logit`` — max over value-pair combos in
    probability space, then the clamped logit — with every float step in
    dd and the clamp rails emitting the oracle's own f64 constants.  A
    pair is branch-unsafe when ANY of its valid combos carries a
    branch-guard flag (conservative: a flagged non-best combo still
    flags the pair — the best-combo fold itself is only dd-accurate).
    """
    D = _dd()
    sim, combo_valid, branch_unsafe = _dd_property_sim(spec, qf, cf,
                                                       pallas_ok)
    one = D.from_f32(jnp.ones_like(sim[0]))
    p, omp = _dd_map_probability(spec, sim, one)
    # fold the combo axis: max in probability space, carrying the
    # matching complement (combo count is small and static — unrolled)
    ncombo = sim[0].shape[0] // (q * c)
    p3 = (p[0].reshape(q, c, ncombo), p[1].reshape(q, c, ncombo))
    omp3 = (omp[0].reshape(q, c, ncombo), omp[1].reshape(q, c, ncombo))
    valid3 = combo_valid.reshape(q, c, ncombo)
    neg = D.const(-1.0, like=p3[0][:, :, 0])
    best_p = neg
    best_omp = D.const(1.0, like=neg[0])
    for i in range(ncombo):
        pi = (p3[0][:, :, i], p3[1][:, :, i])
        oi = (omp3[0][:, :, i], omp3[1][:, :, i])
        take = valid3[:, :, i] & D.lt(best_p, pi)
        best_p = D.where(take, pi, best_p)
        best_omp = D.where(take, oi, best_omp)
    any_valid = valid3.any(axis=2)

    like = best_p[0]
    eps = D.const(_DD_EPS_P, like=like)
    ome = D.const(1.0 - _DD_EPS_P, like=like)
    below = D.le(best_p, eps)
    above = D.ge(best_p, ome)
    pc = D.clamp(best_p, eps, ome)
    # complement floor far below the real rail: rail lanes are overridden
    # with the oracle's exact constants right after, this only keeps the
    # division finite
    ompc = D.clamp(best_omp, D.const(1e-12, like=like),
                   D.const(1.0, like=like))
    logit = D.log(D.div(pc, ompc))
    logit = D.where(above, D.const(probability_to_logit(1.0), like=like),
                    logit)
    logit = D.where(below, D.const(probability_to_logit(0.0), like=like),
                    logit)
    zero = D.const(0.0, like=like)
    if branch_unsafe is None:
        unsafe_qc = jnp.zeros((q, c), bool)
    else:
        unsafe_qc = (branch_unsafe.reshape(q, c, ncombo)
                     & valid3).any(axis=2)
    return D.where(any_valid, logit, zero), unsafe_qc


def _dd_unsafe_mask(spec, qf, cf, *, value_slots_cap: int) -> jnp.ndarray:
    """(Q, C) bool: pairs whose tensors MAY have truncated the records.

    Certification needs the device counts to be the counts of the FULL
    record values; the padded layout truncates in three places — value
    slots past the auto-growth cap, char widths at the per-property
    width, set sizes at the gram/token tensor width.  The tensors carry
    the evidence conservatively: a saturated slot (length == width,
    count == capacity, all value slots valid at the cap) may or may not
    have truncated, so it flags the pair into the host-rescore residue
    (reason="truncation").  False positives (a value exactly at the
    width) cost one host compare; false negatives cannot happen.
    """
    def side(f):
        valid = f["valid"]
        u = jnp.zeros(valid.shape[:-1], bool)
        if value_slots_cap and valid.shape[-1] >= value_slots_cap:
            u = u | valid.all(axis=-1)
        if spec.kind == F.CHARS:
            width = f["chars"].shape[-1]
            u = u | ((f["length"] >= width) & valid).any(axis=-1)
        elif spec.kind == F.GRAM_SET:
            cap = f["grams"].shape[-1]
            u = u | ((f["gram_count"] >= cap) & valid).any(axis=-1)
        elif spec.kind == F.TOKEN_SET:
            cap = f["tokens"].shape[-1]
            u = u | ((f["token_count"] >= cap) & valid).any(axis=-1)
        return u

    uq = side(qf)                # (Q,)
    uc = side(cf)                # (Q, C)
    return uq[:, None] | uc


def build_dd_rescorer(plan: "F.SchemaFeatures", *,
                      queries_from_rows: bool = True,
                      value_slots_cap: int = 0,
                      pallas_ok: bool = True):
    """The jitted survivor dd-rescore program, or None when no property
    is dd-certifiable.

    Signature::

        fn(qfeats, corpus_feats, query_row, top_index)
          -> (logit_hi (Q, K) f32, logit_lo (Q, K) f32, unsafe (Q, K) bool)

    ``top_index`` is the resolved block's (Q, K) global candidate rows
    (-1 padding gathers row 0, results ignored by the caller);
    ``qfeats`` is ``{}`` under ``queries_from_rows`` (query features
    gather on device from the corpus at ``query_row``, the same
    convention as ``build_corpus_scorer``).  ``logit_hi + logit_lo``
    (summed in f64 on host — exact for a float32 pair) is the dd logit
    over the dd-certifiable device properties; ``unsafe`` marks pairs
    whose tensors may have truncated the records (``_dd_unsafe_mask``).

    Rides ``rescore_retrieved``'s gathered layout: candidate k of query
    q is a specific corpus row, and the dominant single-value CHARS
    shape rides the existing gathered Myers Pallas kernel for its
    integer distance.
    """
    specs = dd_plan_specs(plan)
    if not specs:
        return None
    D = _dd()

    @jax.jit
    def rescore(qfeats, corpus_feats, query_row, top_index):
        q, k = top_index.shape
        rows = jnp.clip(top_index, 0).reshape(-1)
        if queries_from_rows:
            qrows = jnp.clip(query_row, 0)
            qfeats_l = {
                spec.name: {
                    name: jnp.take(arr, qrows, axis=0)
                    for name, arr in corpus_feats[spec.name].items()
                }
                for spec in specs
            }
        else:
            qfeats_l = qfeats
        total = (jnp.zeros((q, k), jnp.float32),
                 jnp.zeros((q, k), jnp.float32))
        unsafe = jnp.zeros((q, k), bool)
        for spec in specs:
            cf = {
                name: jnp.take(arr, rows, axis=0).reshape(
                    (q, k) + arr.shape[1:]
                )
                for name, arr in corpus_feats[spec.name].items()
            }
            qf = qfeats_l[spec.name]
            prop_logit, branch_unsafe = _dd_property_logit(
                spec, qf, cf, q, k, pallas_ok
            )
            total = D.add(total, prop_logit)
            unsafe = unsafe | branch_unsafe | _dd_unsafe_mask(
                spec, qf, cf, value_slots_cap=value_slots_cap
            )
        return total[0], total[1], unsafe

    return rescore


# Process-wide memo of built dd rescorers by plan VALUE fingerprint: many
# workloads (and, in the test suite, many short-lived indexes) share one
# schema shape, and each jitted instance pays its own XLA compiles —
# sharing one instance turns that into per-unique-(plan, shape) compiles
# for the whole process.  Deliberately LOCK-FREE (ISSUE 12: the dd
# rescore introduces no new lock): a concurrent miss builds twice and
# one instance wins the dict slot — benign, the loser is just an extra
# tracing.  Bounded FIFO like engine.explain's per-plan cache.
_DD_CACHE: Dict[tuple, object] = {}
_DD_CACHE_CAP = 64


def _dd_plan_key(plan: "F.SchemaFeatures", extra: tuple) -> tuple:
    key = [extra]
    for s in dd_plan_specs(plan):
        cmp = s.comparator
        key.append((
            s.name, s.kind, float(s.low), float(s.high), s.v, s.chars,
            type(cmp).__name__,
            getattr(cmp, "formula", None),
            float(getattr(cmp, "prefix_scale", 0.0)),
            float(getattr(cmp, "boost_threshold", 0.0)),
            int(getattr(cmp, "max_prefix", 0)),
        ))
    return tuple(key)


def dd_rescorer(plan: "F.SchemaFeatures", *, queries_from_rows: bool = True,
                value_slots_cap: int = 0, pallas_ok: bool = True):
    """Memoized ``build_dd_rescorer`` (None when nothing is certifiable)."""
    specs = dd_plan_specs(plan)
    if not specs:
        return None
    key = _dd_plan_key(plan, (queries_from_rows, value_slots_cap, pallas_ok))
    fn = _DD_CACHE.get(key)
    if fn is None:
        fn = build_dd_rescorer(
            plan, queries_from_rows=queries_from_rows,
            value_slots_cap=value_slots_cap, pallas_ok=pallas_ok,
        )
        if len(_DD_CACHE) >= _DD_CACHE_CAP:
            _DD_CACHE.pop(next(iter(_DD_CACHE)))
        _DD_CACHE[key] = fn
    return fn


# -- per-property pair similarity -------------------------------------------


def _pair_expand(qa: jnp.ndarray, ca: jnp.ndarray) -> tuple:
    """(Q, Vq, ...) x (C, Vc, ...) -> flat (Q*C*Vq*Vc, ...) pair operands.

    The value axes may differ: an http-transform query can carry more
    values than any indexed record, and its extra slots ride a wider query
    tensor instead of forcing a corpus rebuild (engine.device_matcher).
    """
    q, vq = qa.shape[0], qa.shape[1]
    c, vc = ca.shape[0], ca.shape[1]
    rq = qa.shape[2:]
    rc = ca.shape[2:]
    a = jnp.broadcast_to(qa[:, None, :, None], (q, c, vq, vc) + rq)
    b = jnp.broadcast_to(ca[None, :, None, :], (q, c, vq, vc) + rc)
    return (a.reshape((q * c * vq * vc,) + rq),
            b.reshape((q * c * vq * vc,) + rc))


def _pair_expand_gathered(qa: jnp.ndarray, ca: jnp.ndarray) -> tuple:
    """(Q, Vq, ...) x gathered (Q, C, Vc, ...) -> flat (Q*C*Vq*Vc, ...).

    The per-query candidate axis is already aligned (candidate row c of
    query q, not a corpus cross product) — used by the ANN rescoring stage.
    """
    q, vq = qa.shape[0], qa.shape[1]
    c, vc = ca.shape[1], ca.shape[2]
    rq = qa.shape[2:]
    rc = ca.shape[3:]
    a = jnp.broadcast_to(qa[:, None, :, None], (q, c, vq, vc) + rq)
    b = jnp.broadcast_to(ca[:, :, None, :], (q, c, vq, vc) + rc)
    return (a.reshape((q * c * vq * vc,) + rq),
            b.reshape((q * c * vq * vc,) + rc))


def _tiled_combo_sim(tile_fn, q: int, c: int, vq: int, vc: int,
                     equal) -> jnp.ndarray:
    """Shared value-combo scaffold for the Pallas tile branches: run a
    (Q, C) tile kernel per (query-value, corpus-value) slot pair and stack
    into the flat (Q*C*Vq*Vc,) layout ``_pair_expand`` produces."""
    eq4 = equal.reshape(q, c, vq, vc)
    rows = []
    for a in range(vq):
        cols = [tile_fn(a, b, eq4[:, :, a, b]) for b in range(vc)]
        rows.append(jnp.stack(cols, axis=-1))         # (Q, C, Vc)
    return jnp.stack(rows, axis=-2).reshape(-1)       # (Q, C, Vq, Vc)


def _property_sim(spec: F.PropertyFeatureSpec, qf: Dict, cf: Dict,
                  expand=_pair_expand, pallas_ok: bool = True,
                  gathered: bool = False) -> tuple:
    """Pair similarity for one property.

    Returns (sim, combo_valid), both flat (Q*C*V*V,).  ``gathered`` marks
    the aligned-candidate layout (cf tensors are (Q, C, V, ...) gathered
    rows, not a corpus cross product) — it selects the gathered Pallas
    branch and disables the cross-product tile branches.
    """
    hh1, hh2 = expand(qf["hash_hi"], cf["hash_hi"])
    hl1, hl2 = expand(qf["hash_lo"], cf["hash_lo"])
    v1, v2 = expand(qf["valid"], cf["valid"])
    combo_valid = v1 & v2
    equal = (hh1 == hh2) & (hl1 == hl2) & combo_valid

    kind = spec.kind
    cmp = spec.comparator
    if (
        gathered
        and pallas_ok
        and kind == F.CHARS
        and not isinstance(cmp, C.JaroWinkler)
        and qf["chars"].shape[1] == 1      # single value slot per side —
        and cf["chars"].shape[2] == 1      # the dominant rescoring shape
        and qf["chars"].shape[2] <= 32
        and pk.pallas_enabled()
    ):
        # ANN rescoring path: candidate chars ride VMEM tiles with the
        # candidate axis on lanes (per-pair text), instead of the flat
        # XLA kernels over expanded (Q*C, L) HBM operands
        q = qf["valid"].shape[0]
        c = cf["valid"].shape[1]
        sim = pk.levenshtein_sim_gathered(
            qf["chars"][:, 0], qf["length"][:, 0],
            cf["chars"][:, :, 0], cf["length"][:, :, 0],
            equal.reshape(q, c),
        ).reshape(-1)
        return sim, combo_valid
    if (
        not gathered
        and
        pallas_ok
        and kind == F.CHARS
        # Levenshtein rides the N-word Myers kernels up to MYERS_MAX_CHARS
        # (256); the Jaro-Winkler tile kernel is single-word bitmask only
        and qf["chars"].shape[2]
        <= (32 if isinstance(cmp, C.JaroWinkler) else pk.MYERS_MAX_CHARS)
        and pk.pallas_enabled()
    ):
        # Pallas tiled path: (TQ, TC) similarity tiles computed in VMEM
        # from O(T*L) operands — no expanded (Q*C, L) pair arrays in HBM.
        if isinstance(cmp, C.JaroWinkler):
            def tile(a, b, eq):
                return pk.jaro_winkler_sim_tiles(
                    qf["chars"][:, a], qf["length"][:, a],
                    cf["chars"][:, b], cf["length"][:, b], eq,
                    prefix_scale=cmp.prefix_scale,
                    boost_threshold=cmp.boost_threshold,
                    max_prefix=int(cmp.max_prefix),
                )
        else:
            def tile(a, b, eq):
                return pk.levenshtein_sim_tiles(
                    qf["chars"][:, a], qf["length"][:, a],
                    cf["chars"][:, b], cf["length"][:, b], eq,
                )
        sim = _tiled_combo_sim(
            tile,
            qf["valid"].shape[0], cf["valid"].shape[0],
            qf["chars"].shape[1], cf["chars"].shape[1], equal,
        )
        return sim, combo_valid
    if (
        not gathered
        and pallas_ok
        and kind in (F.GRAM_SET, F.TOKEN_SET)
        # width guard (mirrors the chars branch's L <= 32): the tile
        # kernel's inner loop unrolls O(G), so a huge DEVICE_MAX_GRAMS /
        # DEVICE_MAX_TOKENS falls back to the flat XLA kernels instead of
        # silently emitting an enormous Mosaic program
        and qf["grams" if kind == F.GRAM_SET else "tokens"].shape[2] <= 256
        and pk.pallas_enabled()
    ):
        # Pallas tiled path: (TQ, TC) intersection tiles in VMEM from
        # O(T*G) operands — no expanded (Q*C, G) pair arrays in HBM.
        if kind == F.GRAM_SET:
            gk, nk, formula = "grams", "gram_count", cmp.formula
        else:
            gk, nk = "tokens", "token_count"
            formula = "dice" if isinstance(cmp, C.DiceCoefficient) else "jaccard"
        sim = _tiled_combo_sim(
            lambda a, b, eq: pk.set_sim_tiles(
                qf[gk][:, a], qf[nk][:, a],
                cf[gk][:, b], cf[nk][:, b], eq, formula=formula,
            ),
            qf["valid"].shape[0], cf["valid"].shape[0],
            qf["valid"].shape[1], cf["valid"].shape[1], equal,
        )
        return sim, combo_valid
    if kind == F.CHARS:
        c1, c2 = expand(qf["chars"], cf["chars"])
        l1, l2 = expand(qf["length"], cf["length"])
        if isinstance(cmp, C.JaroWinkler):
            sim = pw.jaro_winkler_sim(
                c1, l1, c2, l2, equal,
                prefix_scale=cmp.prefix_scale,
                boost_threshold=cmp.boost_threshold,
                max_prefix=int(cmp.max_prefix),
            )
        else:
            sim = pw.levenshtein_sim(c1, l1, c2, l2, equal)
    elif kind == F.CHARS_WEIGHTED:
        c1, c2 = expand(qf["chars"], cf["chars"])
        k1, k2 = expand(qf["classes"], cf["classes"])
        l1, l2 = expand(qf["length"], cf["length"])
        sim = pw.weighted_levenshtein_sim(
            c1, k1, l1, c2, k2, l2, equal,
            digit_weight=cmp.digit_weight,
            letter_weight=cmp.letter_weight,
            other_weight=cmp.other_weight,
        )
    elif kind == F.GRAM_SET:
        g1, g2 = expand(qf["grams"], cf["grams"])
        n1, n2 = expand(qf["gram_count"], cf["gram_count"])
        sim = pw.qgram_sim(g1, n1, g2, n2, equal, formula=cmp.formula)
    elif kind == F.TOKEN_SET:
        t1, t2 = expand(qf["tokens"], cf["tokens"])
        n1, n2 = expand(qf["token_count"], cf["token_count"])
        sim = pw.token_set_sim(
            t1, n1, t2, n2, equal, dice=isinstance(cmp, C.DiceCoefficient)
        )
    elif kind == F.HASH:
        sim = (
            pw.different_sim(equal)
            if isinstance(cmp, C.Different)
            else pw.exact_sim(equal)
        )
    elif kind == F.PHONETIC:
        ch1, ch2 = expand(qf["code_hi"], cf["code_hi"])
        cl1, cl2 = expand(qf["code_lo"], cf["code_lo"])
        cv1, cv2 = expand(qf["code_valid"], cf["code_valid"])
        sim = pw.phonetic_sim(equal, (ch1 == ch2) & (cl1 == cl2), cv1 & cv2)
    elif kind == F.NUMERIC:
        d1, d2 = expand(qf["number"], cf["number"])
        nv1, nv2 = expand(qf["number_valid"], cf["number_valid"])
        sim = pw.numeric_sim(d1, nv1, d2, nv2, min_ratio=cmp.min_ratio)
    elif kind == F.GEO:
        la1, la2 = expand(qf["lat"], cf["lat"])
        lo1, lo2 = expand(qf["lon"], cf["lon"])
        gv1, gv2 = expand(qf["geo_valid"], cf["geo_valid"])
        sim = pw.geoposition_sim(
            la1, lo1, gv1, la2, lo2, gv2, max_distance=cmp.max_distance
        )
    else:  # pragma: no cover - plan() never emits unknown kinds
        raise ValueError(f"no device kernel for feature kind {kind!r}")
    return sim, combo_valid


def _property_logit(spec: F.PropertyFeatureSpec, qf: Dict, cf: Dict,
                    q: int, c: int, expand=_pair_expand,
                    pallas_ok: bool = True,
                    gathered: bool = False) -> jnp.ndarray:
    """Per-pair clamped log-odds contribution of one property: (Q, C) f32.

    Duke's PropertyImpl.compare map (core.records.Property.compare_probability):
    sim >= 0.5 -> (high-0.5)*sim^2 + 0.5, else -> low; properties missing on
    either side are neutral (prob 0.5 -> logit 0).  Max over value-pair
    combos is taken in probability space — the map is applied per combo, so
    semantics match the host engine even for low > 0.5 configs.
    """
    sim, combo_valid = _property_sim(spec, qf, cf, expand, pallas_ok,
                                     gathered)
    prob = jnp.where(
        sim >= 0.5, (spec.high - 0.5) * sim * sim + 0.5, jnp.float32(spec.low)
    )
    prob = jnp.where(combo_valid, prob, -1.0)
    # the trailing (Vq*Vc) combo axis folds away; Vq may differ from Vc
    prob4 = prob.reshape(q, c, -1)
    valid4 = combo_valid.reshape(q, c, -1)
    best = prob4.max(axis=2)
    any_valid = valid4.any(axis=2)
    best = jnp.where(any_valid, best, 0.5)
    best = jnp.clip(best, _EPS, 1.0 - _EPS)
    return jnp.log(best) - jnp.log1p(-best)


def build_pair_logits(plan: F.SchemaFeatures) -> Callable:
    """Returns fn(qfeats, cfeats) -> (Q, C) partial logit over device props."""

    specs = list(plan.device_props)

    def pair_logits(qfeats: Dict[str, Dict], cfeats: Dict[str, Dict]) -> jnp.ndarray:
        first = next(iter(qfeats.values()))
        q = first["valid"].shape[0]
        firstc = next(iter(cfeats.values()))
        c = firstc["valid"].shape[0]
        total = jnp.zeros((q, c), jnp.float32)
        for spec in specs:
            total = total + _property_logit(
                spec, qfeats[spec.name], cfeats[spec.name], q, c
            )
        return total

    return pair_logits


def build_property_logits(plan: F.SchemaFeatures) -> Callable:
    """The ``explain=True`` variant of ``build_pair_logits``: returns
    fn(qfeats, cfeats) -> (Q, C, P) with the PER-PROPERTY clamped
    log-odds vector kept un-reduced (axis P follows
    ``plan.device_props`` order).  Sums over P to the same pair logit
    the fast path computes — same kernels, same probability map, same
    clamps — but lives as a SEPARATE builder so the jitted fast path
    (``build_pair_logits``/``scan_topk``) is never perturbed by explain
    traffic.  Pallas tile branches are disabled (``pallas_ok=False``):
    explain calls score a handful of pairs, where the flat XLA kernels
    avoid compiling Mosaic programs for one-off shapes.

    Used by the decision-explainability layer (engine.explain) to
    reproduce a pair's device f32 verdict with per-property provenance.
    """

    specs = list(plan.device_props)

    def property_logits(qfeats: Dict[str, Dict],
                        cfeats: Dict[str, Dict]) -> jnp.ndarray:
        first = next(iter(qfeats.values()))
        q = first["valid"].shape[0]
        firstc = next(iter(cfeats.values()))
        c = firstc["valid"].shape[0]
        per_prop = [
            _property_logit(spec, qfeats[spec.name], cfeats[spec.name],
                            q, c, pallas_ok=False)
            for spec in specs
        ]
        return jnp.stack(per_prop, axis=-1)  # (Q, C, P)

    return property_logits


def candidate_mask(cvalid, cdeleted, cgroup, cidx, query_group, query_row,
                   group_filtering: bool):
    """(Q, chunk) candidate-eligibility mask shared by every retrieval path.

    Policy (one place, so brute-force and ANN retrieval can never diverge):
    live non-tombstoned rows only; linkage excludes same-group rows
    (IncrementalLuceneDatabase.java:467-475); a query never matches its own
    corpus row.

    One other site encodes this same policy and must stay in sync: the
    fused Pallas retrieval mask (ops.encoder._fused_retrieval /
    ops.pallas_kernels._retrieval_segmax_kernel), which packs it into an
    int8 per-row encoding because a Mosaic kernel cannot consume the
    boolean columns directly.
    """
    mask = cvalid & ~cdeleted
    if group_filtering:
        mask = mask & (cgroup[None, :] != query_group[:, None])
    return mask & (cidx[None, :] != query_row[:, None])


def candidate_mask_gathered(gvalid, gdeleted, ggroup, grows, query_group,
                            query_row, group_filtering: bool):
    """``candidate_mask`` for ALIGNED gathered candidates: all operands
    are (Q, S) per-query gathers (IVF probe scan, ops.ivf) plus the
    global row ids ``grows`` (-1 for padding slots).  Same policy, same
    one place: live non-tombstoned, group exclusion, self-row exclusion
    — plus the padding-slot exclusion the gathered layout introduces."""
    mask = (grows >= 0) & gvalid & ~gdeleted
    if group_filtering:
        mask = mask & (ggroup != query_group[:, None])
    return mask & (grows != query_row[:, None])


def retrieval_amb_eps(q_tree, emb_tree):
    """Quantization ambiguity credit for the recall-escalation trigger:
    the certified per-block cosine error bound under int8 storage
    (``ops.encoder.int8_cosine_eps_dynamic`` — derived from the block's
    ACTUAL row scales), or None for float storage (where the trigger
    stays exactly the pre-int8 predicate)."""
    from . import encoder as E

    if E.is_int8_tree(emb_tree):
        return E.int8_cosine_eps_dynamic(q_tree, emb_tree)
    return None


def saturation_count(logits, top_sim, retrieved, min_logit, amb_eps):
    """ONE copy of the escalation-count predicate shared by every
    retrieval tail (single-device flat/IVF and the per-shard sharded
    tails): above-``min_logit`` candidates, plus — under int8 storage —
    the quantization-ambiguity credit.

    ``amb_eps`` (None for float storage) widens the saturation trigger:
    when the retrieved set is FULL, a candidate whose retrieval cosine
    sits within ``2 * amb_eps`` of the top-C cutoff AND whose exact
    rescore clears the pruning bound counts as saturation evidence a
    second time — a true candidate displaced by quantization error (the
    dropped one's exact cosine can exceed the cutoff by at most 2*eps)
    is cosine-adjacent to exactly these band members, and if they matter
    after rescoring, the dropped neighbor could too, so the search
    escalates instead of silently eating recall.  The above-bound
    conjunct is what keeps the credit a *saturation* signal and not a
    tail-density detector: it reasons from rescored evidence, the same
    way the original "every retrieved candidate cleared the bound"
    predicate does — a dense cosine tail of non-matches at the cutoff
    (the common no-match query) takes no credit and cannot ladder
    (measured: the unconditioned band escalated routinely on the
    stresstest corpus; this form matches the bf16 path's zero).  With
    the credit absent (or eps 0) this is bit-identical to the pre-int8
    predicate (no retrieved cosine is strictly below the cutoff).  A
    non-full retrieved set means retrieval never truncated, so no
    ambiguity credit applies (and tiny corpora cannot trigger pointless
    escalation ladders)."""
    import jax.numpy as jnp

    above = logits > min_logit
    count = above.sum(axis=1).astype(jnp.int32)
    if amb_eps is not None:
        full = retrieved.all(axis=1)
        cutoff = top_sim[:, -1:]  # sorted desc: the smallest retrieved
        amb = ((top_sim < cutoff + 2.0 * amb_eps)
               & retrieved & above).sum(axis=1).astype(jnp.int32)
        count = count + jnp.where(full, amb, 0)
    return count


def rescore_retrieved(pair_logits, qfeats, corpus_feats, top_sim, top_index,
                      min_logit, *, amb_eps=None):
    """The shared tail of every two-stage retrieval program (flat ANN and
    IVF): gather the retrieved rows' feature tensors, score them with the
    exact per-property kernels, and derive the escalation count
    (``saturation_count`` — ``amb_eps`` documented there)."""
    import jax.numpy as jnp

    retrieved = top_index >= 0
    top_c = top_index.shape[1]
    rows = jnp.clip(top_index, 0).reshape(-1)
    q = top_index.shape[0]
    cfeats = {
        prop: {
            name: jnp.take(arr, rows, axis=0).reshape(
                (q, top_c) + arr.shape[1:]
            )
            for name, arr in tensors.items()
        }
        for prop, tensors in corpus_feats.items()
    }
    logits = pair_logits(qfeats, cfeats)
    logits = jnp.where(retrieved, logits, NEG_INF)
    count = saturation_count(logits, top_sim, retrieved, min_logit, amb_eps)
    return logits, top_index, count


def build_gathered_pair_logits(plan: F.SchemaFeatures) -> Callable:
    """Returns fn(qfeats (Q,...), cfeats gathered (Q, C, ...)) -> (Q, C).

    The aligned-candidate variant of ``build_pair_logits`` used by the ANN
    rescoring stage: candidate c of query q is a specific gathered corpus
    row, not a cross product.  Levenshtein single-value properties ride
    the gathered Pallas Myers kernel (candidate axis on lanes); other
    kinds use the flat XLA kernels — the pair count here is Q*C, already
    pruned by retrieval.
    """
    specs = list(plan.device_props)

    def pair_logits(qfeats: Dict[str, Dict], cfeats: Dict[str, Dict]) -> jnp.ndarray:
        first = next(iter(cfeats.values()))
        q, c = first["valid"].shape[0], first["valid"].shape[1]
        total = jnp.zeros((q, c), jnp.float32)
        for spec in specs:
            total = total + _property_logit(
                spec, qfeats[spec.name], cfeats[spec.name], q, c,
                expand=_pair_expand_gathered, gathered=True,
            )
        return total

    return pair_logits


def build_ann_scorer(
    plan: F.SchemaFeatures,
    *,
    chunk: int = 512,
    top_c: int = 64,
    group_filtering: bool = False,
    queries_from_rows: bool = False,
) -> Callable:
    """Two-stage ANN scoring program: cosine retrieval + exact rescoring.

    Stage 1 ranks the whole corpus by embedding cosine (ops.encoder — one
    bf16 matmul per chunk, MXU) keeping the top ``top_c`` rows per query;
    stage 2 gathers those rows' feature tensors and scores them with the
    exact per-property kernels.  Returned logits are therefore on the same
    scale (and with the same host-property bound semantics) as
    ``build_corpus_scorer`` — only the candidate *set* is approximate.

    Signature::

        fn(q_emb, qfeats, corpus_emb, corpus_feats, corpus_valid,
           corpus_deleted, corpus_group, query_group, query_row, min_logit)
        -> (top_logit (Q, C), top_index (Q, C), count_above (Q,))

    ``count_above`` saturating at ``top_c`` signals the caller to escalate C
    (recall escalation — the ANN analogue of the brute-force K-escalation).
    Under int8 embedding storage (DUKE_EMB_INT8) the count additionally
    credits quantization-ambiguous candidates at the retrieval cutoff —
    see ``rescore_retrieved``.

    ``corpus_emb`` (and ``q_emb`` when not from rows) accept the
    ANN_PROP tensor dict — ``{emb}`` for bf16 storage, ``{emb, scale}``
    for int8 — or a bare bf16 matrix (legacy convention).

    ``queries_from_rows``: as in ``build_corpus_scorer`` — ``q_emb`` and
    ``qfeats`` are ignored (pass empty placeholders) and both are gathered
    on device from the corpus at ``query_row``.
    """
    from . import encoder as E

    pair_logits = build_gathered_pair_logits(plan)

    @jax.jit
    def score(q_emb, qfeats, corpus_emb, corpus_feats, corpus_valid,
              corpus_deleted, corpus_group, query_group, query_row,
              min_logit):
        emb_tree = E.as_emb_tree(corpus_emb)
        if queries_from_rows:
            qrows = jnp.clip(query_row, 0)
            q_tree = {
                name: jnp.take(arr, qrows, axis=0)
                for name, arr in emb_tree.items()
            }
            qfeats = gather_rows(corpus_feats, qrows)
        else:
            q_tree = E.as_emb_tree(q_emb)
        top_sim, top_index = E.retrieval_scan(
            q_tree, emb_tree, corpus_valid, corpus_deleted, corpus_group,
            query_group, query_row,
            chunk=chunk, top_c=top_c, group_filtering=group_filtering,
        )
        return rescore_retrieved(
            pair_logits, qfeats, corpus_feats, top_sim, top_index,
            min_logit, amb_eps=retrieval_amb_eps(q_tree, emb_tree),
        )

    return score


# -- the blockwise corpus scorer --------------------------------------------


@dataclass
class ScoreResult:
    """Top-K device scores for a query block (numpy, already fetched)."""

    top_logit: np.ndarray   # (Q, K) partial device logit, NEG_INF when empty
    top_index: np.ndarray   # (Q, K) corpus row index
    count_above: np.ndarray  # (Q,) candidates whose optimistic prob clears min threshold


def scan_topk(
    pair_logits: Callable,
    qfeats,
    corpus_feats,
    corpus_valid,
    corpus_deleted,
    corpus_group,
    query_group,
    query_row,
    min_logit,
    *,
    chunk: int,
    top_k: int,
    group_filtering: bool,
    row_offset=0,
    init=None,
):
    """The blockwise scan core: scores Q queries against a (local) corpus.

    ``row_offset`` maps local corpus rows to global row ids — 0 on a single
    device; ``shard_index * shard_capacity`` inside ``shard_map`` (see
    parallel.sharded), so self-exclusion via ``query_row`` and the returned
    ``top_index`` stay global.  Traced (non-static) offsets are fine.

    ``init`` seeds the running (top_logit, top_index, count) carry — the
    ring scorer (parallel.ring) threads a query block's accumulated top-K
    through successive corpus shards with it.
    """
    first = next(iter(qfeats.values()))
    q = first["valid"].shape[0]
    cap = corpus_valid.shape[0]
    nchunks = cap // chunk

    if init is not None:
        init_logit, init_index, init_count = init
    else:
        init_logit = jnp.full((q, top_k), NEG_INF, jnp.float32)
        init_index = jnp.full((q, top_k), -1, jnp.int32)
        init_count = jnp.zeros((q,), jnp.int32)

    def body(carry, ci):
        top_logit, top_index, count = carry
        start = ci * chunk
        cf = jax.tree_util.tree_map(
            lambda a: lax.dynamic_slice_in_dim(a, start, chunk, axis=0),
            corpus_feats,
        )
        logits = pair_logits(qfeats, cf)  # (Q, chunk)

        cvalid = lax.dynamic_slice_in_dim(corpus_valid, start, chunk)
        cdel = lax.dynamic_slice_in_dim(corpus_deleted, start, chunk)
        cgroup = lax.dynamic_slice_in_dim(corpus_group, start, chunk)
        cidx = row_offset + start + jnp.arange(chunk, dtype=jnp.int32)

        mask = candidate_mask(
            cvalid, cdel, cgroup, cidx, query_group, query_row,
            group_filtering,
        )
        logits = jnp.where(mask, logits, NEG_INF)

        count = count + (logits > min_logit).sum(axis=1).astype(jnp.int32)

        merged_logit = jnp.concatenate([top_logit, logits], axis=1)
        merged_index = jnp.concatenate(
            [top_index, jnp.broadcast_to(cidx[None, :], (q, chunk))], axis=1
        )
        top_logit, sel = lax.top_k(merged_logit, top_k)
        top_index = jnp.take_along_axis(merged_index, sel, axis=1)
        return (top_logit, top_index, count), None

    (top_logit, top_index, count), _ = lax.scan(
        body, (init_logit, init_index, init_count),
        jnp.arange(nchunks, dtype=jnp.int32),
    )
    return top_logit, top_index, count


def gather_rows(tree, rows: jnp.ndarray):
    """Gather record rows out of a corpus feature tree (on device)."""
    return jax.tree_util.tree_map(
        lambda arr: jnp.take(arr, rows, axis=0), tree
    )


def build_corpus_scorer(
    plan: F.SchemaFeatures,
    *,
    chunk: int = 512,
    top_k: int = 64,
    group_filtering: bool = False,
    queries_from_rows: bool = False,
) -> Callable:
    """Build the jitted query-block x corpus scorer.

    Returned callable signature::

        fn(qfeats, corpus_feats, corpus_valid, corpus_deleted, corpus_group,
           query_group, query_row, min_logit) -> (top_logit, top_index, count_above)

    ``corpus_*`` arrays are padded to a capacity that is a multiple of
    ``chunk``; recompiles only when the capacity changes (doubling growth).
    ``query_row`` is each query's own corpus row (-1 when not indexed, e.g.
    http-transform) for self-pair exclusion; ``min_logit`` is
    logit(min(threshold, maybe_threshold)) minus the host-property bound.

    With ``queries_from_rows`` the ``qfeats`` argument is ignored (pass an
    empty dict) and query features are gathered **on device** from the
    corpus at ``query_row`` — the common dedup/linkage case where the query
    batch was just indexed.  This keeps the per-batch host->device traffic
    to one small int32 array instead of re-uploading every query feature
    tensor (the dominant steady-state cost over a high-latency device
    link).  Padding rows (-1) gather row 0; their results are discarded by
    the caller.
    """

    pair_logits = build_pair_logits(plan)

    @partial(jax.jit, static_argnames=())
    def score(qfeats, corpus_feats, corpus_valid, corpus_deleted, corpus_group,
              query_group, query_row, min_logit):
        if queries_from_rows:
            qfeats = gather_rows(corpus_feats, jnp.clip(query_row, 0))
        return scan_topk(
            pair_logits, qfeats, corpus_feats, corpus_valid, corpus_deleted,
            corpus_group, query_group, query_row, min_logit,
            chunk=chunk, top_k=top_k, group_filtering=group_filtering,
        )

    return score


def logit_to_probability(logit: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.asarray(logit, dtype=np.float64)))
