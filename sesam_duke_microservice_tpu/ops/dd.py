"""Two-float ("double-double" style) emulated-f64 arithmetic for TPU.

TPUs have no float64 ALU, but the certified-finalization path (ISSUE 12,
``ops.scoring``/``engine.finalize``) needs device results close enough to
the host's exact f64 ``Processor.compare`` that most survivor verdicts can
be *certified* on device.  This module provides the classic double-float
representation: a value is an unevaluated sum ``hi + lo`` of two float32s
with ``|lo| <= ulp(hi)/2``, giving ~49 bits of significand — 2^25x the
precision of a bare float32, and comfortably past f64's 53 bits once the
certified margin (``ops.scoring.certified_dd_margin``) charges every
operation its worst-case rounding.

Safety under XLA: the building blocks are the *error-free transforms*
(Knuth two-sum, Dekker split / two-product) whose correctness needs only
that individual float32 ``+ - *`` are IEEE-rounded — true of the TPU VPU
and of XLA's CPU/GPU backends.  What is NOT safe is leaving the
transforms visible to the compiler.  Two distinct passes break them:

  * the HLO algebraic simplifier cancels patterns like ``x - (x - a)``
    — the heart of every EFT — to ``a``, turning an exact error term
    into literal zero (measured: a jitted ``1 - num/den`` lost its low
    word entirely, 2.2e-8 error vs 3e-16 eager);
  * the CPU/GPU backends FMA-contract ``a*b + c``, skipping the
    product's own rounding (measured: ``fl(ln2*k) + e`` emitted as
    ``fma(ln2, k, e)``, a full f32-ulp shift of ``log``'s result —
    1e-6 at logit scale — even though the optimized HLO was correct).

Every rounded intermediate inside the EFTs is therefore committed
through ``lax.reduce_precision(x, 8, 23)`` — numerically the identity
for a float32, but an opaque op both passes must preserve, and one that
still fuses (``optimization_barrier`` also works but fragments the
kernel).  ``tests/test_dd.py`` runs the JITTED ops against the f64
oracle to keep this honest.  No transcendental is trusted: ``log`` is
computed from the atanh series with exactly-representable power-of-two
argument reduction, so its error is a provable function of the dd
operation count, not of a libm/vendor polynomial.

Representation notes
  * every public function takes/returns ``(hi, lo)`` tuples of same-shape
    jnp arrays (float32);
  * ``const(x)`` / ``from_float(x)`` split a *Python f64* into a dd pair
    reproducing it to ~2^-48 relative — used for schema constants
    (``high``, ``low``, thresholds) so the device computes with the same
    f64 values the host oracle uses;
  * integers up to 2^24 are exact in a single float32 (``from_int``) —
    the comparator counts (edit distances, set sizes, lengths) all fit.

Error model used by the certified margin: each dd ``add``/``mul``/``div``
is accurate to a relative ``DD_EPS = 2^-44`` (the true bounds are
~2^-47..2^-49; the slack absorbs the host side's own f64 rounding and any
looseness in the published double-float theorems), and ``log`` to
``LOG_ERR_ABS + DD_EPS * |result|`` absolute.  ``tests/test_dd.py`` holds
randomized sweeps of every op against the Python-f64 oracle at a tenth of
these budgets.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

# Per-operation relative error budget charged by certified_dd_margin
# (deliberately generous — see module docstring).  The ledger derivation
# below covers the published double-float worst cases at f32 unit
# roundoff u32 = 2^-24: accurate add22 <= 3u^2, Dekker mul22 <= 5u^2,
# long division with two corrections <= 12u^2 (scripts/dukecheck/budgets
# re-derives these in interval arithmetic and fails CI if this constant
# ever stops covering them — see docs/ERROR_BUDGETS.md).
# dd-budget: DD_EPS covers max(3*u32**2, 5*u32**2, 12*u32**2) headroom 1.25
DD_EPS = 2.0 ** -44
# Absolute error budget of log() beyond the DD_EPS-relative term: series
# truncation (2^-55-level) + ~40 dd ops on operands of magnitude <= 1.3
# (the reduced mantissa path; the k*ln2 term rides the relative part).
# Validated with further headroom by tests/test_dd.py's oracle sweeps.
# dd-budget: LOG_ERR_ABS covers 40 * 1.3 * DD_EPS + 2**-55 headroom 1.2
LOG_ERR_ABS = 2.0 ** -38

DD = Tuple[jnp.ndarray, jnp.ndarray]

_SPLITTER = np.float32(4097.0)  # 2^12 + 1 (Dekker split for 24-bit floats)


# -- error-free transforms ----------------------------------------------------
#
# Every rounded intermediate inside an EFT is COMMITTED through
# ``lax.reduce_precision(x, 8, 23)`` — numerically the identity for a
# float32, but an opaque HLO op that (a) stops the algebraic simplifier
# from cancelling patterns like ``x - (x - a)`` into ``a`` (which turns
# an exact error term into literal zero), and (b) stops the CPU/GPU
# backends from FMA-contracting ``a*b + c`` (measured: ``fl(a*b) + e``
# emitted as ``fma(a, b, e)`` skipped the product's rounding and shifted
# the k*ln2 term of ``log`` by a full f32 ulp, 1e-6 at logit scale).
# Unlike ``optimization_barrier`` it fuses, so the dd pipeline still
# compiles to a handful of kernels.


def _f32(x):
    """Commit ``x`` to its float32-rounded value (see block comment)."""
    return lax.reduce_precision(x, exponent_bits=8, mantissa_bits=23)


def two_sum(a, b):
    """Knuth two-sum: s + e == a + b exactly, s = fl(a + b)."""
    s = _f32(a + b)
    bb = _f32(s - a)
    e = _f32(_f32(a - _f32(s - bb)) + _f32(b - bb))
    return s, e


def fast_two_sum(a, b):
    """Dekker quick-two-sum; requires |a| >= |b| (or a == 0)."""
    s = _f32(a + b)
    e = _f32(b - _f32(s - a))
    return s, e


def split(a):
    """Dekker split: a == hi + lo with hi, lo 12-bit-significand halves."""
    t = _f32(a * _SPLITTER)
    hi = _f32(t - _f32(t - a))
    return hi, _f32(a - hi)


def two_prod(a, b):
    """p + e == a * b exactly, p = fl(a * b)."""
    p = _f32(a * b)
    ah, al = split(a)
    bh, bl = split(b)
    e = _f32(
        _f32(_f32(_f32(_f32(ah * bh) - p) + _f32(ah * bl)) + _f32(al * bh))
        + _f32(al * bl)
    )
    return p, e


# -- construction -------------------------------------------------------------


def from_f32(a) -> DD:
    """Lift a float32 array (exactly) into dd."""
    a = jnp.asarray(a, jnp.float32)
    return a, jnp.zeros_like(a)


def from_int(i) -> DD:
    """Exact dd from integer arrays with |i| < 2^24 (comparator counts)."""
    return from_f32(jnp.asarray(i).astype(jnp.float32))


def const_pair(x: float) -> Tuple[np.float32, np.float32]:
    """Host-side split of a Python f64 into (hi, lo) float32 scalars.

    Reproduces ``x`` to ~2^-48 relative — the residual is charged to
    ``DD_EPS`` by the margin.  Used for every schema constant so the
    device arithmetic runs on (a dd image of) the same f64 values the
    host oracle's expressions produce.
    """
    hi = np.float32(x)
    lo = np.float32(x - float(hi))
    return hi, lo


def const(x: float, like=None) -> DD:
    """``const_pair`` broadcast as jnp scalars (or like-shaped arrays)."""
    hi, lo = const_pair(x)
    if like is None:
        return jnp.float32(hi), jnp.float32(lo)
    return (jnp.full_like(like, hi, dtype=jnp.float32),
            jnp.full_like(like, lo, dtype=jnp.float32))


def to_f64(x: DD) -> np.ndarray:
    """Host-side exact read-back: f64(hi) + f64(lo) (both exact in f64)."""
    return (np.asarray(x[0], dtype=np.float64)
            + np.asarray(x[1], dtype=np.float64))


# -- arithmetic ---------------------------------------------------------------


def neg(x: DD) -> DD:
    return -x[0], -x[1]


def add(x: DD, y: DD) -> DD:
    """Accurate dd addition (add22 with both low-order terms folded)."""
    s, e = two_sum(x[0], y[0])
    t, f = two_sum(x[1], y[1])
    e = _f32(e + t)
    s, e = fast_two_sum(s, e)
    e = _f32(e + f)
    return fast_two_sum(s, e)


def sub(x: DD, y: DD) -> DD:
    return add(x, neg(y))


def mul(x: DD, y: DD) -> DD:
    """dd multiplication (mul22): two-product + cross terms."""
    p, e = two_prod(x[0], y[0])
    e = _f32(e + _f32(_f32(x[0] * y[1]) + _f32(x[1] * y[0])))
    return fast_two_sum(p, e)


def div(x: DD, y: DD) -> DD:
    """dd division via long division with two correction terms.

    Denominators on the scoring path are >= 1e-10 in magnitude (clamped
    probabilities, integer counts >= 1), far from float32's denormal
    floor, so no scaling pass is needed.
    """
    q1 = _f32(x[0] / y[0])
    r = sub(x, mul(y, from_f32(q1)))
    q2 = _f32(r[0] / y[0])
    r = sub(r, mul(y, from_f32(q2)))
    q3 = _f32(r[0] / y[0])
    s, e = fast_two_sum(q1, q2)
    return fast_two_sum(s, _f32(e + q3))


def scale_pow2(x: DD, k) -> DD:
    """Multiply by 2^k (k integer array) — exact, no rounding.

    Committed anyway: the products feed EFT adds downstream, and a
    contraction there must see an opaque operand, not a multiply."""
    s = jnp.ldexp(jnp.float32(1.0), k).astype(jnp.float32)
    return _f32(x[0] * s), _f32(x[1] * s)


# -- comparisons / selection --------------------------------------------------


def lt(x: DD, y: DD):
    return (x[0] < y[0]) | ((x[0] == y[0]) & (x[1] < y[1]))


def le(x: DD, y: DD):
    return (x[0] < y[0]) | ((x[0] == y[0]) & (x[1] <= y[1]))


def ge(x: DD, y: DD):
    return le(y, x)


def where(cond, x: DD, y: DD) -> DD:
    return jnp.where(cond, x[0], y[0]), jnp.where(cond, x[1], y[1])


def maximum(x: DD, y: DD) -> DD:
    return where(lt(x, y), y, x)


def minimum(x: DD, y: DD) -> DD:
    return where(lt(x, y), x, y)


def clamp(x: DD, lo: DD, hi: DD) -> DD:
    return minimum(maximum(x, lo), hi)


# -- logarithm ----------------------------------------------------------------

# ln(2) as a dd constant (error ~2^-49 relative; charged to DD_EPS via
# the k*ln2 term in LOG_ERR_ABS).
_LN2 = const_pair(math.log(2.0))
# atanh-series order: |t| <= sqrt(2)-1 / (sqrt(2)+1) = 0.1716, so term
# k decays by t^2 ~ 2^-5.08; 11 terms put the truncation tail below
# 2^-55 relative — under the dd arithmetic noise floor.
_LOG_TERMS = 11
_SQRT_HALF = np.float32(0.7071067811865476)


def log(x: DD) -> DD:
    """Natural log of a positive dd value.

    Argument reduction is exactly representable: ``x = m * 2^k`` with
    ``m`` in [sqrt(1/2), sqrt(2)) via frexp + a power-of-two rescale of
    both components (no rounding), then ``ln m = 2 atanh(t)`` with
    ``t = (m-1)/(m+1)`` summed as the odd atanh series in dd, plus
    ``k * ln2`` from the dd ln2 constant.  No libm transcendental
    participates, so the error bound (``LOG_ERR_ABS`` absolute +
    ``DD_EPS`` relative) follows from the dd op count alone.

    Domain: finite positive ``x``; scoring clamps its probabilities into
    [1e-10, 1-1e-10] first, so inputs sit in [~1e-10, ~1e10].
    """
    m, k = jnp.frexp(x[0])  # m in [0.5, 1)
    adjust = m < _SQRT_HALF
    k = (k - adjust.astype(k.dtype)).astype(jnp.int32)  # dukecheck: ignore[DK602] integer exponent arithmetic — exact, nothing to commit
    mx = scale_pow2(x, -k)  # in [sqrt(1/2), sqrt(2))
    one = from_f32(jnp.ones_like(x[0]))
    t = div(sub(mx, one), add(mx, one))
    t2 = mul(t, t)
    s = const(1.0 / (2 * _LOG_TERMS + 1), like=x[0])
    for i in range(_LOG_TERMS - 1, -1, -1):
        s = add(mul(s, t2), const(1.0 / (2 * i + 1), like=x[0]))
    r = mul(t, s)
    r = add(r, r)  # 2 * t * series
    kf = k.astype(jnp.float32)  # |k| <= ~128: exact in f32
    ln2 = (jnp.full_like(x[0], _LN2[0]), jnp.full_like(x[0], _LN2[1]))
    return add(r, mul(ln2, from_f32(kf)))
