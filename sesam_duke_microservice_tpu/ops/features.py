"""Host-side per-record feature extraction for the device scoring path.

Design: the O(N) per-record work (unicode handling, hashing, phonetic codes,
numeric parsing, tokenization) stays on the host where strings are natural;
the O(N^2) per-pair work runs on device over the padded tensors produced
here.  This replaces the reference's per-pair string handling inside Duke
comparators (SURVEY.md section 1 L1) with a tokenize-once/compare-many split.

Each schema property is assigned a *feature kind* based on its comparator
class; ``extract_batch`` turns a list of records into a dict of numpy arrays
per property, every array shaped ``(N, V, ...)`` where ``V`` is the number of
value slots (Duke records are multi-valued; pair probability is the max over
value pairs — Processor.compare / ops.scoring).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import comparators as C
from ..core.config import DukeSchema
from ..core.records import Record

# Static shape defaults (device tensors are padded to these; chars/grams
# beyond the padded width are truncated — documented in tests/test_ops.py;
# the *value* axis auto-sizes to the data in engine.device_matcher, so
# multi-valued records are not truncated below DEVICE_VALUE_SLOTS_MAX).
# Env-tunable: the CPU test backend uses smaller
# shapes (tests/conftest.py) since it executes the kernels without an MXU.
# MAX_CHARS defaults to 32 so edit distance rides the Myers bit-parallel
# kernel (one uint32 word per pattern, ~100x the scan-DP throughput);
# DEVICE_MAX_CHARS=64 restores 64-char fidelity via the general DP.
from ..telemetry.env import env_int

MAX_CHARS = env_int("DEVICE_MAX_CHARS", 32)
MAX_GRAMS = env_int("DEVICE_MAX_GRAMS", 64)
MAX_TOKENS = env_int("DEVICE_MAX_TOKENS", 16)

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF
# values longer than this hash on the scalar path (vectorization pads to
# the bucket max; a lone multi-KB value must not inflate the whole batch)
_BATCH_HASH_MAX_BYTES = 4096

# Sentinel for empty sorted-set slots: int32 max sorts last.
SET_PAD = np.int32(2**31 - 1)

# Char tensors hold UTF-16 CODE UNITS in uint16 (r5) — not uint32
# codepoints.  Halves the dominant HBM/row term, the restart upload, the
# snapshot, and the bootstrap payload at once, and it is the reference's
# own text model: Duke comparators run on java.lang.String char units,
# so a surrogate pair counts as TWO units there too (e.g.
# Levenshtein.java operates per char).  The host comparators apply the
# same expansion for non-BMP text (core.comparators._utf16_expand), so
# host and device distances stay bit-identical.
CHAR_DTYPE = np.uint16


def char_units(value: str) -> int:
    """Length of ``value`` in UTF-16 code units (the char-axis unit)."""
    if value.isascii():  # O(1) flag check — the ingest hot path's case
        return len(value)
    # C-speed for the non-ASCII remainder (no Python per-char loop)
    return len(value.encode("utf-16-le", "surrogatepass")) >> 1


def fnv1a64(value: str) -> int:
    h = _FNV_OFFSET
    # surrogatepass: json.loads accepts lone surrogates, so record values can
    # contain them; hashing must be total (cf. native/__init__.py utf-32)
    for b in value.encode("utf-8", "surrogatepass"):
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def fnv1a64_batch(values: Sequence[str]) -> np.ndarray:
    """Vectorized ``fnv1a64`` over many strings -> (N,) uint64.

    Bit-identical to the scalar loop (differential-tested).  Fast path:
    one bulk C pass over the concatenated UTF-8 bytes
    (``native.fnv1a64_bytes_batch`` — the ingest path hashes every value
    plus every q-gram/token per record, and this was the top profiled
    ingest cost).  Fallback: the numpy fold over byte POSITIONS
    (vectorized across values, O(max_len) numpy ops).
    """
    n = len(values)
    out = np.full((n,), _FNV_OFFSET, dtype=np.uint64)
    if n == 0:
        return out
    bufs = [v.encode("utf-8", "surrogatepass") for v in values]
    from .. import native

    if native.available():
        return native.fnv1a64_bytes_batch(bufs)
    # group by byte-length power of two: a naive single padded matrix is
    # O(n * maxlen), so ONE long outlier value (arbitrary JSON fields) in
    # a big batch would balloon both the matrix and the fold loop; within
    # a bucket padding waste is <= 2x, and oversized values take the
    # scalar path
    groups: Dict[int, List[int]] = {}
    for idx, b in enumerate(bufs):
        length = len(b)
        if length == 0:
            continue
        if length > _BATCH_HASH_MAX_BYTES:
            h = _FNV_OFFSET
            for byte in b:
                h = ((h ^ byte) * _FNV_PRIME) & _MASK64
            out[idx] = h
            continue
        groups.setdefault((length - 1).bit_length(), []).append(idx)
    prime = np.uint64(_FNV_PRIME)
    for idxs in groups.values():
        gbufs = [bufs[i] for i in idxs]
        lens = np.fromiter((len(b) for b in gbufs), dtype=np.int64,
                           count=len(gbufs))
        maxlen = int(lens.max())
        mat = np.zeros((len(gbufs), maxlen), dtype=np.uint64)
        for row, b in enumerate(gbufs):
            mat[row, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        acc = np.full((len(gbufs),), _FNV_OFFSET, dtype=np.uint64)
        for j in range(maxlen):
            active = lens > j
            h = (acc ^ mat[:, j]) * prime  # uint64 wraps mod 2^64 (the mask)
            acc = np.where(active, h, acc)
        out[np.asarray(idxs)] = acc
    return out


def _split2x32(h: np.ndarray):
    """(hi, lo) int32 views of (N,) uint64 hashes (matches _hash2x32)."""
    lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32).view(np.int32)
    hi = (h >> np.uint64(32)).astype(np.uint32).view(np.int32)
    return hi, lo


def _fold32(h: np.ndarray) -> np.ndarray:
    """(N,) int32 folded hashes (matches _hash32)."""
    return ((h ^ (h >> np.uint64(32))) & np.uint64(0xFFFFFFFF)).astype(
        np.uint32
    ).view(np.int32)


def _hash2x32(value: str) -> tuple:
    h = fnv1a64(value)
    lo = np.int64(h & 0xFFFFFFFF).astype(np.int32)
    hi = np.int64(h >> 32).astype(np.int32)
    return hi, lo


def _hash32(value: str) -> np.int32:
    h = fnv1a64(value)
    return np.int64((h ^ (h >> 32)) & 0xFFFFFFFF).astype(np.int32)


# -- feature kinds -----------------------------------------------------------

CHARS = "chars"              # padded codepoints + length (+ hash)
CHARS_WEIGHTED = "chars_w"   # chars + per-char class for weighted edits
GRAM_SET = "gram_set"        # sorted distinct q-gram hashes
TOKEN_SET = "token_set"      # sorted distinct token hashes
HASH = "hash"                # value hash only (exact/different)
PHONETIC = "phonetic"        # value hash + phonetic code hash
NUMERIC = "numeric"          # parsed float
GEO = "geo"                  # parsed lat/lon

# THE kind registry.  dukecheck's numerics gate (DK604) reads this tuple
# statically and asserts every member has a ``_SIM_ERROR_BOUND`` entry
# and is partitioned into ``DD_KINDS``/``DD_FALLBACK_KINDS`` in
# ops.scoring — add a kind here without its budget-table entries and CI
# fails instead of the new kind silently collapsing the certified
# margins (an absent entry reads as inf/uncertifiable at runtime).
ALL_KINDS = (CHARS, CHARS_WEIGHTED, GRAM_SET, TOKEN_SET, HASH, PHONETIC,
             NUMERIC, GEO)


def feature_kind(comparator) -> Optional[str]:
    """Feature kind for a comparator instance, or None if the comparator has
    no device kernel yet (scored on host via the hybrid pruning path —
    engine.device_matcher)."""
    if comparator is None:
        return None
    if isinstance(comparator, C.WeightedLevenshtein):
        return CHARS_WEIGHTED
    if isinstance(comparator, (C.Levenshtein, C.JaroWinkler)) and not isinstance(
        comparator, C.JaroWinklerTokenized
    ):
        return CHARS
    if isinstance(comparator, C.QGram):
        return GRAM_SET
    if isinstance(comparator, (C.JaccardIndex, C.DiceCoefficient)):
        return TOKEN_SET
    if isinstance(comparator, (C.Exact, C.Different)):
        return HASH
    if isinstance(comparator, (C.Soundex, C.Metaphone, C.Norphone)):
        return PHONETIC
    if isinstance(comparator, C.Numeric):
        return NUMERIC
    if isinstance(comparator, C.Geoposition):
        return GEO
    return None


def _phonetic_code(comparator, value: str) -> str:
    if isinstance(comparator, C.Soundex):
        return C.soundex(value)
    if isinstance(comparator, C.Metaphone):
        return C.metaphone(value)
    return C.norphone(value)


@dataclass
class PropertyFeatureSpec:
    """Static description of one schema property's device representation."""

    name: str
    kind: str
    low: float
    high: float
    comparator: object
    values_per_record: int = 1
    # per-property char-tensor width (CHARS kinds): starts at the global
    # MAX_CHARS default and auto-grows with the data in
    # engine.device_matcher, so ONE long-text property widens its own
    # tensors (and rides the scan-DP fallback past MYERS_MAX_CHARS)
    # without dragging every short property off the 32-char Myers path
    max_chars: int = 0

    @property
    def v(self) -> int:
        return self.values_per_record

    @property
    def chars(self) -> int:
        return self.max_chars or MAX_CHARS


@dataclass
class SchemaFeatures:
    """Per-schema feature plan: which properties score on device vs host."""

    device_props: List[PropertyFeatureSpec] = field(default_factory=list)
    host_props: List = field(default_factory=list)  # core Property objects

    @classmethod
    def plan(cls, schema: DukeSchema, values_per_record: int = 1) -> "SchemaFeatures":
        plan = cls()
        for prop in schema.comparison_properties():
            kind = feature_kind(prop.comparator)
            if kind is None:
                plan.host_props.append(prop)
            else:
                plan.device_props.append(
                    PropertyFeatureSpec(
                        name=prop.name,
                        kind=kind,
                        low=prop.low,
                        high=prop.high,
                        comparator=prop.comparator,
                        values_per_record=values_per_record,
                    )
                )
        return plan


# -- extraction --------------------------------------------------------------


def _char_class(ch: str) -> int:
    if ch.isdigit():
        return 2
    if ch.isalpha():
        return 1
    return 0


def extract_property(
    spec: PropertyFeatureSpec, values_per_record: Sequence[List[str]]
) -> Dict[str, np.ndarray]:
    """Extract one property's features for N records.

    ``values_per_record[i]`` is record i's (cleaned, non-empty) value list
    for this property; slots beyond ``spec.v`` are dropped (Duke scores the
    max over all value pairs; we bound the value axis for static shapes).
    """
    n = len(values_per_record)
    v = spec.v
    out: Dict[str, np.ndarray] = {}
    valid = np.zeros((n, v), dtype=bool)
    hash_hi = np.zeros((n, v), dtype=np.int32)
    hash_lo = np.zeros((n, v), dtype=np.int32)

    kind = spec.kind
    if kind in (CHARS, CHARS_WEIGHTED):
        L = spec.chars
        chars = np.zeros((n, v, L), dtype=CHAR_DTYPE)
        length = np.zeros((n, v), dtype=np.int32)
        classes = (
            np.zeros((n, v, L), dtype=np.int32)
            if kind == CHARS_WEIGHTED
            else None
        )
    elif kind == GRAM_SET:
        grams = np.full((n, v, MAX_GRAMS), SET_PAD, dtype=np.int32)
        gram_count = np.zeros((n, v), dtype=np.int32)
        q = int(getattr(spec.comparator, "q", 2))
    elif kind == TOKEN_SET:
        tokens = np.full((n, v, MAX_TOKENS), SET_PAD, dtype=np.int32)
        token_count = np.zeros((n, v), dtype=np.int32)
    elif kind == PHONETIC:
        code_hi = np.zeros((n, v), dtype=np.int32)
        code_lo = np.zeros((n, v), dtype=np.int32)
        code_valid = np.zeros((n, v), dtype=bool)
    elif kind == NUMERIC:
        number = np.zeros((n, v), dtype=np.float32)
        number_valid = np.zeros((n, v), dtype=bool)
    elif kind == GEO:
        lat = np.zeros((n, v), dtype=np.float32)
        lon = np.zeros((n, v), dtype=np.float32)
        geo_valid = np.zeros((n, v), dtype=bool)

    # flatten the ragged (record, slot) structure once; value hashing is
    # then ONE vectorized fnv pass instead of a Python byte loop per value
    flat: List[tuple] = [
        (i, k, value)
        for i, values in enumerate(values_per_record)
        for k, value in enumerate(values[:v])
    ]
    if flat:
        m = len(flat)
        ii = np.fromiter((t[0] for t in flat), dtype=np.int64, count=m)
        kk = np.fromiter((t[1] for t in flat), dtype=np.int64, count=m)
        hi, lo = _split2x32(fnv1a64_batch([t[2] for t in flat]))
        valid[ii, kk] = True
        hash_hi[ii, kk] = hi
        hash_lo[ii, kk] = lo

    if kind in (CHARS, CHARS_WEIGHTED):
        if flat:
            # utf-16-le: text rides the device as UTF-16 CODE UNITS in
            # uint16 — half the HBM/row, upload, snapshot, and bootstrap
            # bytes of the old uint32 codepoints, and EXACT parity with
            # the reference, whose comparators run on java.lang.String
            # char units (Duke Levenshtein.distance etc. count a
            # surrogate PAIR as two units).  surrogatepass round-trips
            # lone surrogates; slicing the byte buffer at 2*L may split
            # a pair, which is precisely Java's substring-on-code-units
            # behavior.  One concatenated buffer + boolean-mask scatter
            # fills the whole (m, L) block (row-major mask order ==
            # concatenation order).
            # slice to L CHARS first so a multi-KB value pays O(L), not
            # O(len), per extraction; L chars cover >= L code units, so
            # the byte cap after encoding is exact
            bufs = [
                t[2][:L].encode("utf-16-le", "surrogatepass")[: 2 * L]
                for t in flat
            ]
            m = len(flat)
            lens = np.fromiter((len(b) >> 1 for b in bufs), np.int64,
                               count=m)
            mat = np.zeros((m, L), dtype=CHAR_DTYPE)
            if int(lens.sum()):
                all_cu = np.frombuffer(b"".join(bufs), dtype="<u2")
                mat[np.arange(L)[None, :] < lens[:, None]] = all_cu
            chars[ii, kk] = mat  # ii/kk from the hash block above
            length[ii, kk] = lens.astype(np.int32)
            if classes is not None:
                # per-UNIT character classes.  Surrogate units class as
                # "other" (0): Java's Character.isDigit/isLetter on a
                # lone surrogate char is false, and the host path sees
                # the same after _utf16_expand — all three agree.
                for i, k, value in flat:
                    j = 0
                    for ch in value:
                        if ord(ch) > 0xFFFF:
                            if j < L:
                                classes[i, k, j] = 0
                            if j + 1 < L:
                                classes[i, k, j + 1] = 0
                            j += 2
                        else:
                            if j < L:
                                classes[i, k, j] = _char_class(ch)
                            j += 1
                        if j >= L:
                            break
    elif kind == GRAM_SET:
        from .. import native

        if flat and native.available():
            # one bulk C pass: window + UTF-8 + hash + dedupe + sort per
            # value (replaces ~5 gram-substring Python objects + one
            # str.encode per window — the top ingest cost after hashing)
            gmat, gcounts = native.gram_set_batch(
                [t[2] for t in flat], q, MAX_GRAMS, int(SET_PAD)
            )
            grams[ii, kk] = gmat
            gram_count[ii, kk] = gcounts
        else:
            # one flat hash pass over every gram of every value
            gram_lists = [C.qgrams(t[2], q) for t in flat]
            all_ids = _fold32(
                fnv1a64_batch([g for gl in gram_lists for g in gl])
            )
            pos = 0
            for (i, k, _), gl in zip(flat, gram_lists):
                ids = sorted(set(all_ids[pos:pos + len(gl)].tolist()))
                pos += len(gl)
                ids = ids[:MAX_GRAMS]
                grams[i, k, : len(ids)] = ids
                gram_count[i, k] = len(ids)
    elif kind == TOKEN_SET:
        token_lists = [t[2].split() for t in flat]
        all_ids = _fold32(
            fnv1a64_batch([t for tl in token_lists for t in tl])
        )
        pos = 0
        for (i, k, _), tl in zip(flat, token_lists):
            ids = sorted(set(all_ids[pos:pos + len(tl)].tolist()))
            pos += len(tl)
            ids = ids[:MAX_TOKENS]
            tokens[i, k, : len(ids)] = ids
            token_count[i, k] = len(ids)
    elif kind == PHONETIC:
        codes = [_phonetic_code(spec.comparator, t[2]) for t in flat]
        chi, clo = _split2x32(fnv1a64_batch(codes))
        for idx, (i, k, _) in enumerate(flat):
            if codes[idx]:
                code_hi[i, k] = chi[idx]
                code_lo[i, k] = clo[idx]
                code_valid[i, k] = True
    elif kind == NUMERIC:
        for i, k, value in flat:
            try:
                d = float(value)
                if np.isfinite(d):
                    number[i, k] = np.float32(d)
                    number_valid[i, k] = True
            except (TypeError, ValueError):
                pass
    elif kind == GEO:
        for i, k, value in flat:
            parsed = C.Geoposition._parse(value)
            if parsed is not None:
                lat[i, k] = np.float32(parsed[0])
                lon[i, k] = np.float32(parsed[1])
                geo_valid[i, k] = True

    out["valid"] = valid
    out["hash_hi"] = hash_hi
    out["hash_lo"] = hash_lo
    if kind in (CHARS, CHARS_WEIGHTED):
        out["chars"] = chars
        out["length"] = length
        if classes is not None:
            out["classes"] = classes
    elif kind == GRAM_SET:
        out["grams"] = grams
        out["gram_count"] = gram_count
    elif kind == TOKEN_SET:
        out["tokens"] = tokens
        out["token_count"] = token_count
    elif kind == PHONETIC:
        out["code_hi"] = code_hi
        out["code_lo"] = code_lo
        out["code_valid"] = code_valid
    elif kind == NUMERIC:
        out["number"] = number
        out["number_valid"] = number_valid
    elif kind == GEO:
        out["lat"] = lat
        out["lon"] = lon
        out["geo_valid"] = geo_valid
    return out


def extract_batch(
    plan: SchemaFeatures, records: Sequence[Record], *, encoder=None
) -> Dict[str, Dict[str, np.ndarray]]:
    """Extract all device-scored properties for a batch of records.

    Returns ``{property_name: {tensor_name: (N, V, ...) array}}``; when
    ``encoder`` is given (the ANN backend), the embedding rides in the
    result under its pseudo-property.

    THE one extraction entry point — corpus appends, plan-change
    rebuilds, and query-side probe extraction all come through here — so
    the digest-keyed feature cache (ops.feature_cache,
    ``DUKE_FEATURE_CACHE_MB``) sits here too: rows whose record content
    and feature plan both match a cached entry scatter from the cache,
    and only the misses run the extraction below.  A Sesam full resync
    re-POSTs mostly-unchanged entities, so steady-state re-encode is
    mostly cache hits.
    """
    if records:
        from . import feature_cache as FC

        cache = FC.active()
        if cache is not None:
            return FC.cached_extract(cache, plan, records, encoder=encoder)
    return _extract_direct(plan, records, encoder=encoder)


def _extract_direct(
    plan: SchemaFeatures, records: Sequence[Record], *, encoder=None
) -> Dict[str, Dict[str, np.ndarray]]:
    """Cache-bypassing extraction (the feature cache's miss path).

    Serial below a slab threshold.  Parallel variants were measured in
    r4: a thread fan-out gains nothing because the remaining per-value
    glue (string encode, flat-list construction, embedding packing) is
    GIL-bound Python — the C/numpy bulk passes it feeds already release
    the GIL but no longer dominate; a spawn process pool returning
    tensors LOSES 3-5x to pickling + IPC of ~1 KB/row both ways.  r5
    adds the fix that analysis pointed at: bulk slabs fan out to a
    process pool whose workers write tensors straight into shared
    memory (ops.parallel_extract) — only the much smaller record values
    ride the task pickle.  The serial-path wins (bulk C FNV hashing,
    q-gram set extraction, one-pass scatter) apply inside each worker.
    """
    from . import encoder as E

    if len(records) >= 1:
        from . import parallel_extract as PX

        if PX.enabled(len(records)):
            out = PX.extract_batch_parallel(plan, records, encoder=encoder)
            if out is not None:
                return out

    out = _extract_serial(plan, records)
    if encoder is not None:
        # storage-mode-aware: {emb} bf16, or {emb, scale} under
        # DUKE_EMB_INT8 (the scale vector rides the corpus tree as a
        # second ANN_PROP tensor)
        out[E.ANN_PROP] = encoder.corpus_tensors(records)
    return out


def _extract_serial(
    plan: SchemaFeatures, records: Sequence[Record]
) -> Dict[str, Dict[str, np.ndarray]]:
    out: Dict[str, Dict[str, np.ndarray]] = {}
    empty: List[str] = []
    for spec in plan.device_props:
        # read-only peek at the live value lists (get_values copies per
        # call — measurable at 10^5-record slabs x several properties);
        # stored values are never empty (Record.add_value drops them)
        name = spec.name
        values = [r._values.get(name, empty) for r in records]
        out[spec.name] = extract_property(spec, values)
    return out


def concat_features(
    parts: Sequence[Dict[str, Dict[str, np.ndarray]]]
) -> Dict[str, Dict[str, np.ndarray]]:
    """Concatenate per-batch feature dicts along the record axis."""
    if not parts:
        return {}
    out: Dict[str, Dict[str, np.ndarray]] = {}
    for prop in parts[0]:
        out[prop] = {
            name: np.concatenate([p[prop][name] for p in parts], axis=0)
            for name in parts[0][prop]
        }
    return out
