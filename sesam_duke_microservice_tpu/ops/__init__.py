"""TPU compute path: feature extraction + batched pairwise scoring.

The reference's hot loop is per-pair virtual dispatch into Duke comparator
classes (SURVEY.md section 3.2 hot loop 1, driven from App.java:1005/1159).
Here that loop becomes a data-parallel device program:

  * ``features``  — per-record O(N) feature extraction on host (tokenize,
    hash, phonetic codes, numeric parse); produces padded numpy tensors.
  * ``pairwise``  — per-pair O(N^2 / block) similarity kernels in JAX
    (edit-distance wavefront via cumulative-min, Jaro-Winkler scan,
    sorted-set intersection by batched binary search, scalar compares).
  * ``scoring``   — assembles per-property kernels + the naive-Bayes
    log-odds combine into one jitted blockwise scoring program.
"""
