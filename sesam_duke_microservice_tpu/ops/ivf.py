"""IVF clustered retrieval: k-means cells + two-stage probe scoring.

Breaks the flat-scan FLOP wall of the embedding-ANN backend
(``ops.encoder.retrieval_scan`` touches every corpus row per query, so
retrieval work is O(N * D) per query regardless of how concentrated the
corpus is).  The standard billion-scale playbook (FAISS IVF cell-probe,
SCANN quantized scoring) applies cleanly here because exact f64
finalization already makes retrieval a *recall-only* concern:

  * **train** — k-means over the corpus embeddings (device matmul
    assignment steps, host centroid fold; seeded, so training is
    deterministic for a given corpus + platform).  Trained lazily the
    first time a scoring pass sees the corpus past ``DUKE_IVF_MIN_ROWS``
    and refreshed when the corpus doubles past the trained size — both
    under the workload lock the scoring path already holds, so the
    trainer needs NO new lock.
  * **bucket** — every row is assigned to its nearest centroid;
    assignments are incremental (a streaming append assigns only the new
    slice — ingest never retrains) and live in a padded ``(cells, B)``
    row-index matrix so the probe program keeps static shapes.
  * **probe** — per query: one tiny (Q, K) query x centroid matmul picks
    the top-``nprobe`` cells, then a masked candidate scan scores ONLY
    those cells' rows (gathered embedding tiles, the same
    dtype-dispatched MXU scoring as the flat scan incl. DUKE_EMB_INT8)
    keeping a running top-C.  Retrieval FLOPs drop from N*D to
    ~(K + nprobe*B)*D per query — ~10x at nprobe ~ sqrt(K).

Safety net: the exact rescoring of retrieved pairs is UNCHANGED (shared
``ops.scoring`` tail), and a saturated probe escalates ``nprobe`` with
the C-escalation ladder until it degenerates to the flat scan
(``engine.ann_matcher``) — truncation can never pass silently, exactly
like today's top-C doubling.  ``DUKE_IVF=0`` (default) never constructs
any of this.

Sharded layout: cell membership is stored as a stacked
``(nshards * K, B)`` matrix of shard-LOCAL row ids — shard s's block is
rows [s*K, (s+1)*K).  On one device (nshards=1) local == global; on a
mesh the matrix is placed record-axis sharded (``P(SHARD_AXIS)``) so
each shard_map instance sees exactly its own (K, B) block, while the
tiny centroid matrix rides replicated (``P()``) — the SNIPPETS.md
pjit partition-rule pattern (shard the big per-row state, replicate the
small lookup tables).
"""

from __future__ import annotations

import logging
import math
from typing import Dict, Optional

import numpy as np

from ..telemetry.env import env_flag, env_int
from . import encoder as E

logger = logging.getLogger("ivf")


def enabled() -> bool:
    """The IVF master switch (read at index construction — the resolved
    choice then rides the feature-cache fingerprint)."""
    return env_flag("DUKE_IVF", False)


def min_rows() -> int:
    """Corpus size below which IVF stays untrained (the flat scan is
    already cheap there and k-means would overfit a tiny corpus)."""
    return env_int("DUKE_IVF_MIN_ROWS", 4096)


def configured_cells(n_rows: int) -> int:
    """Cell count: DUKE_IVF_CELLS, or the ~sqrt(N) auto policy bucketed
    to a power of two (so corpus growth re-trains onto O(log N) distinct
    probe-program shapes, mirroring the capacity-doubling discipline)."""
    k = env_int("DUKE_IVF_CELLS", 0)
    if k <= 0:
        k = 1 << max(2, math.ceil(math.log2(max(4.0, math.sqrt(n_rows)))))
    return max(2, min(k, max(2, n_rows // 2)))


def configured_nprobe(ncells: int) -> int:
    """Initial probed-cell count: DUKE_IVF_NPROBE, or ~sqrt(K) auto."""
    p = env_int("DUKE_IVF_NPROBE", 0)
    if p <= 0:
        p = max(1, int(round(math.sqrt(ncells))))
    return max(1, min(p, ncells))


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


def _slot_bucket(n: int) -> int:
    """Membership-matrix width bucket: pow2 up to 64, then 64-multiples.
    Cells are size-skewed in practice (embeddings cluster by the data's
    name distribution), and a pow2 width driven by the LARGEST cell pads
    every probed cell to it — the probe scan's FLOPs scale with the
    padded width, so the coarser-than-necessary pow2 step was measurably
    eating the IVF FLOP win.  64-multiples keep recompiles rare (widths
    only change on overflow rebuilds, which double-ish) at ~1/4 the
    padding waste."""
    if n <= 64:
        return _pow2(max(1, n))
    return -(-n // 64) * 64


# -- k-means ------------------------------------------------------------------


def _kmeans_step():
    """Jitted one-Lloyd-step kernel: cosine assignment (argmax over the
    X @ C^T matmul — rows and centroids are L2-normalized, so cosine and
    squared-distance argmins coincide) plus per-cell sums/counts for the
    host-side centroid fold.  Shapes (n, D) x (K, D); recompiles per
    (n, K) bucket — training is rare by construction."""
    import jax
    import jax.numpy as jnp

    def step(x, cents):
        scores = x @ cents.T                       # (n, K) f32
        assign = jnp.argmax(scores, axis=1).astype(jnp.int32)
        k = cents.shape[0]
        sums = jax.ops.segment_sum(x, assign, num_segments=k)
        counts = jax.ops.segment_sum(
            jnp.ones((x.shape[0],), jnp.float32), assign, num_segments=k
        )
        return assign, sums, counts

    return jax.jit(step)


def train_kmeans(x: np.ndarray, ncells: int, *, seed: int,
                 iters: int) -> np.ndarray:
    """Deterministic seeded k-means over L2-normalized rows ``x``.

    Returns (ncells, D) f32 L2-normalized centroids.  Init is a seeded
    row sample (deterministic for a given corpus + seed); each Lloyd
    step runs the assignment matmul on device and folds centroids on
    host.  Empty cells keep their previous centroid (they stay probeable
    and can re-acquire rows on the next refresh)."""
    n = x.shape[0]
    rng = np.random.default_rng(seed)
    init = rng.choice(n, size=min(ncells, n), replace=False)
    cents = x[np.sort(init)].astype(np.float32).copy()
    if cents.shape[0] < ncells:  # degenerate tiny corpus: repeat rows
        reps = -(-ncells // cents.shape[0])
        cents = np.tile(cents, (reps, 1))[:ncells]
    norms = np.linalg.norm(cents, axis=1, keepdims=True)
    cents /= np.where(norms > 0.0, norms, 1.0)

    import jax

    step = _kmeans_step()
    xj = None
    for _ in range(max(1, iters)):
        if xj is None:
            import jax.numpy as jnp

            xj = jnp.asarray(x, dtype=jnp.float32)
        _, sums, counts = jax.device_get(step(xj, cents))
        nonempty = counts > 0.0
        folded = sums / np.where(nonempty, counts, 1.0)[:, None]
        cents = np.where(nonempty[:, None], folded, cents).astype(np.float32)
        norms = np.linalg.norm(cents, axis=1, keepdims=True)
        cents /= np.where(norms > 0.0, norms, 1.0)
    return cents


class IvfState:
    """Lazy-trained IVF index over one corpus's embedding rows.

    All mutation happens on the scoring path, which runs UNDER the
    workload lock (``_AnnScorerCache.dispatch_block``) — no lock of its
    own.  Host state is authoritative; device copies re-place lazily per
    generation through the owning scorer cache's placement hooks.
    """

    def __init__(self, *, nshards: int = 1, seed: Optional[int] = None):
        self.nshards = max(1, nshards)
        self.seed = seed if seed is not None else env_int(
            "DUKE_IVF_SEED", 1234
        )
        self.iters = env_int("DUKE_IVF_ITERS", 8)
        self.centroids: Optional[np.ndarray] = None   # (K, D) f32
        self.ncells = 0
        self.nprobe0 = 0
        self.cell_of = np.full((0,), -1, dtype=np.int32)  # per corpus row
        self.cell_rows: Optional[np.ndarray] = None   # (nshards*K, B) local
        self.counts: Optional[np.ndarray] = None      # (nshards, K)
        self.bucket = 0                               # B (pow2)
        self.assigned_upto = 0
        self.trained_rows = 0
        self.generation = 0       # bumps on any centroid/membership change
        self._corpus_id: Optional[int] = None
        self._local_cap = 0
        self._assign_fn = None
        # device mirrors, re-placed when generation moves (placement hook
        # injected by the scorer cache: replicated vs mesh-sharded)
        self._dev: Optional[tuple] = None
        self._dev_gen = -1

    # -- queries -------------------------------------------------------------

    @property
    def ready(self) -> bool:
        return self.centroids is not None

    def nprobe_for(self, top_c: int, initial_top_c: int) -> int:
        """Probed cells at escalation width ``top_c``: the initial
        ``nprobe`` scaled with the C-doubling ladder, so a saturated
        probe widens its cell coverage in lockstep with its candidate
        budget; at >= ncells the caller falls back to the flat scan."""
        grow = max(1, top_c // max(1, initial_top_c))
        return min(self.ncells, max(1, self.nprobe0 * grow))

    # -- maintenance (workload lock held) ------------------------------------

    def sync(self, corpus) -> bool:
        """Bring the IVF state up to date with ``corpus``; returns
        readiness.  Trains lazily past ``min_rows``, refreshes (full
        retrain + reassignment) once the corpus doubles past the trained
        size, and otherwise assigns only the appended slice — streaming
        ingest never retrains."""
        if self._corpus_id != id(corpus):
            # corpus object replaced (value-slot rebuild, fresh index):
            # row numbering restarted, so membership must too
            self._reset()
            self._corpus_id = id(corpus)
        if not self.ready and corpus.size < min_rows():
            return False
        retrain = (
            not self.ready
            or corpus.size >= 2 * max(1, self.trained_rows)
        )
        if retrain:
            self._train(corpus)
        if self.ready:
            self._assign_new(corpus)
        return self.ready

    def _reset(self) -> None:
        self.centroids = None
        self.ncells = 0
        self.cell_of = np.full((0,), -1, dtype=np.int32)
        self.cell_rows = None
        self.counts = None
        self.bucket = 0
        self.assigned_upto = 0
        self.trained_rows = 0
        self._local_cap = 0
        self.generation += 1
        self._dev = None

    def _embeddings_f32(self, corpus, lo: int, hi: int) -> np.ndarray:
        return E.dequantize_rows({
            name: arr[lo:hi]
            for name, arr in corpus.feats[E.ANN_PROP].items()
        })

    def _train(self, corpus) -> None:
        n = corpus.size
        live = np.flatnonzero(
            corpus.row_valid[:n] & ~corpus.row_deleted[:n]
        )
        if live.size < 2:
            return
        # train on a seeded sample so a 10M-row refresh does not
        # materialize (or matmul) the full f32 corpus per Lloyd step —
        # gather the sampled rows out of the compact storage FIRST, then
        # dequantize only those (the sample bound must bound host RAM
        # too, not just the matmul)
        sample_max = env_int("DUKE_IVF_TRAIN_SAMPLE", 262144)
        rows = live
        if live.size > sample_max:
            rng = np.random.default_rng(self.seed)
            rows = np.sort(rng.choice(live, size=sample_max, replace=False))
        x = E.dequantize_rows({
            name: arr[rows]
            for name, arr in corpus.feats[E.ANN_PROP].items()
        })
        self.ncells = configured_cells(live.size)
        self.centroids = train_kmeans(
            x, self.ncells, seed=self.seed, iters=self.iters
        )
        self.nprobe0 = configured_nprobe(self.ncells)
        self.trained_rows = n
        # full reassignment under the fresh centroids
        self.cell_of = np.full((corpus.capacity,), -1, dtype=np.int32)
        self.assigned_upto = 0
        self.cell_rows = None
        self.generation += 1
        self._dev = None
        self._assign_new(corpus)
        logger.info(
            "IVF trained: %d cells over %d rows (nprobe0=%d, bucket=%d)",
            self.ncells, int(live.size), self.nprobe0, self.bucket,
        )

    def _assign_rows(self, x: np.ndarray) -> np.ndarray:
        """Nearest-centroid assignment for a slice of f32 rows (device
        matmul; tiny next to the append's own extraction)."""
        if self._assign_fn is None:
            import jax
            import jax.numpy as jnp

            self._assign_fn = jax.jit(
                lambda a, c: jnp.argmax(a @ c.T, axis=1).astype(jnp.int32)
            )
        import jax

        return np.asarray(jax.device_get(
            self._assign_fn(x, self.centroids)
        ))

    def _assign_new(self, corpus) -> None:
        if self.cell_of.shape[0] < corpus.capacity:
            grown = np.full((corpus.capacity,), -1, dtype=np.int32)
            grown[: self.cell_of.shape[0]] = self.cell_of
            self.cell_of = grown
        lo, hi = self.assigned_upto, corpus.size
        if hi > lo:
            step = 65536
            for s in range(lo, hi, step):
                e = min(hi, s + step)
                self.cell_of[s:e] = self._assign_rows(
                    self._embeddings_f32(corpus, s, e)
                )
            self.assigned_upto = hi
            self.generation += 1
            self._dev = None
        self._rebuild_membership(corpus, lo)

    def _rebuild_membership(self, corpus, appended_from: int) -> None:
        """Maintain the padded (nshards*K, B) local-row membership
        matrix.  Incremental for appended rows; a bucket overflow (some
        cell outgrew B) or a capacity/shard-layout change rebuilds from
        ``cell_of`` wholesale (O(N log N), rare by the pow2 bucketing)."""
        local_cap = corpus.capacity // self.nshards
        if (
            self.cell_rows is None
            or self._local_cap != local_cap
        ):
            self._rebuild_full(corpus, local_cap)
            return
        rows = np.arange(appended_from, self.assigned_upto)
        if rows.size == 0:
            return
        shard = rows // local_cap
        cells = self.cell_of[rows]
        key = shard * self.ncells + cells
        need = np.bincount(
            key, minlength=self.nshards * self.ncells,
        ).reshape(self.nshards, self.ncells)
        if (self.counts + need).max() > self.bucket:
            self._rebuild_full(corpus, local_cap)
            return
        # vectorized grouped scatter (the _rebuild_full trick with the
        # live counts as base offsets): a large streaming append must not
        # run a per-row Python loop under the workload lock
        order = np.argsort(key, kind="stable")
        sorted_key = key[order]
        starts = np.searchsorted(
            sorted_key, np.arange(self.nshards * self.ncells)
        )
        rank = np.arange(rows.size) - starts[sorted_key]
        slots = self.counts.reshape(-1)[sorted_key] + rank
        self.cell_rows[sorted_key, slots] = (
            rows[order] - shard[order] * local_cap
        ).astype(np.int32)
        self.counts += need
        self.generation += 1
        self._dev = None

    def _rebuild_full(self, corpus, local_cap: int) -> None:
        n = self.assigned_upto
        self._local_cap = local_cap
        rows = np.arange(n)
        shard = rows // max(1, local_cap)
        cells = self.cell_of[:n]
        key = shard * self.ncells + cells
        counts = np.bincount(
            key, minlength=self.nshards * self.ncells
        ).reshape(self.nshards, self.ncells)
        self.bucket = _slot_bucket(int(counts.max(initial=1)))
        self.counts = counts
        mat = np.full(
            (self.nshards * self.ncells, self.bucket), -1, dtype=np.int32
        )
        order = np.argsort(key, kind="stable")
        sorted_key = key[order]
        # slot index within each (shard, cell) run of the sorted order
        starts = np.searchsorted(sorted_key, np.arange(
            self.nshards * self.ncells
        ))
        slot = np.arange(n) - starts[sorted_key]
        mat[sorted_key, slot] = (rows[order]
                                 - shard[order] * local_cap).astype(np.int32)
        self.cell_rows = mat
        self.generation += 1
        self._dev = None

    # -- device placement ----------------------------------------------------

    def device_tensors(self, place_centroids=None, place_cells=None):
        """(centroids, cell_rows) as device arrays, re-placed when the
        generation moved.  ``place_*`` hooks inject sharding: the default
        single-device placement, or replicated centroids + record-axis
        sharded membership on a mesh."""
        if self._dev is None or self._dev_gen != self.generation:
            import jax.numpy as jnp

            pc = place_centroids or jnp.asarray
            pk = place_cells or jnp.asarray
            self._dev = (pc(self.centroids), pk(self.cell_rows))
            self._dev_gen = self.generation
        return self._dev


# -- the probe program core ---------------------------------------------------


def _dequant_j(q_tree: Dict):
    import jax.numpy as jnp

    emb = q_tree[E.ANN_TENSOR]
    if E.ANN_SCALE in q_tree:
        return emb.astype(jnp.float32) * q_tree[E.ANN_SCALE][:, None]
    return emb.astype(jnp.float32)


def scan_slots() -> int:
    """Candidate-slot chunk of the probe scan: bounds the transient
    (Q, slots, D) gathered-embedding tile."""
    return env_int("DUKE_IVF_SCAN_SLOTS", 1024)


def ivf_probe_topc(q_tree, emb_tree, centroids, cell_rows, corpus_valid,
                   corpus_deleted, corpus_group, query_group, query_row, *,
                   top_c: int, nprobe: int, slot_chunk: int,
                   group_filtering: bool, row_offset=0):
    """Two-stage cell-probe retrieval: (top_sim, top_index) with GLOBAL
    row indices, same contract as ``ops.encoder.retrieval_scan``.

    Usable both under plain jit (row_offset=0) and inside shard_map
    (``cell_rows`` is the shard's local (K, B) block of local row ids;
    ``row_offset`` maps them to global ids, exactly as in
    ``parallel.sharded``'s scan).  The eligibility mask is
    ``ops.scoring.candidate_mask_gathered`` — the one-place policy.
    """
    import jax.numpy as jnp
    from jax import lax

    from . import scoring as S

    qf = _dequant_j(q_tree)                      # (Q, D) f32
    q = qf.shape[0]
    cell_scores = qf @ centroids.T.astype(jnp.float32)   # (Q, K) tiny
    _, cells = lax.top_k(cell_scores, nprobe)            # (Q, P)
    bucket = cell_rows.shape[1]
    cand = jnp.take(cell_rows, cells.reshape(-1), axis=0).reshape(
        q, nprobe * bucket
    )                                            # local rows, -1 padded
    total = nprobe * bucket
    step = min(slot_chunk, _pow2(total))
    nsteps = -(-total // step)
    if nsteps * step != total:
        cand = jnp.pad(cand, ((0, 0), (0, nsteps * step - total)),
                       constant_values=-1)

    emb = emb_tree[E.ANN_TENSOR]
    scale = emb_tree.get(E.ANN_SCALE)
    neg = jnp.float32(S.NEG_INF)
    init_sim = jnp.full((q, top_c), neg, jnp.float32)
    init_idx = jnp.full((q, top_c), -1, jnp.int32)

    def body(carry, si):
        top_sim, top_idx = carry
        rows = lax.dynamic_slice_in_dim(cand, si * step, step, axis=1)
        safe = jnp.clip(rows, 0)
        flat = safe.reshape(-1)
        emb_g = jnp.take(emb, flat, axis=0).reshape(q, step, -1)
        if scale is not None:
            raw = jnp.einsum(
                "qd,qsd->qs", q_tree[E.ANN_TENSOR], emb_g,
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32)
            sims = (raw * q_tree[E.ANN_SCALE][:, None]
                    * jnp.take(scale, flat).reshape(q, step))
        else:
            sims = jnp.einsum(
                "qd,qsd->qs",
                q_tree[E.ANN_TENSOR].astype(jnp.bfloat16),
                emb_g.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
        grows = jnp.where(rows >= 0, rows + row_offset, -1)
        mask = S.candidate_mask_gathered(
            jnp.take(corpus_valid, flat).reshape(q, step),
            jnp.take(corpus_deleted, flat).reshape(q, step),
            jnp.take(corpus_group, flat).reshape(q, step),
            grows, query_group, query_row, group_filtering,
        )
        sims = jnp.where(mask, sims, neg)
        # carry first: top_k's positional tie-break keeps -1 sentinels
        # from being displaced by all-masked slots (same invariant as
        # retrieval_scan's merge)
        merged_sim = jnp.concatenate([top_sim, sims], axis=1)
        merged_idx = jnp.concatenate([top_idx, grows], axis=1)
        top_sim, sel = lax.top_k(merged_sim, top_c)
        top_idx = jnp.take_along_axis(merged_idx, sel, axis=1)
        return (top_sim, top_idx), None

    (top_sim, top_idx), _ = lax.scan(
        body, (init_sim, init_idx), jnp.arange(nsteps, dtype=jnp.int32)
    )
    return top_sim, top_idx


def build_ivf_scorer(
    plan,
    *,
    top_c: int,
    nprobe: int,
    group_filtering: bool = False,
    queries_from_rows: bool = False,
) -> "object":
    """The jitted single-device IVF scoring program.

    Signature (the flat ``ops.scoring.build_ann_scorer`` convention plus
    the two IVF tensors)::

        fn(q_emb, qfeats, emb_tree, centroids, cell_rows, corpus_feats,
           corpus_valid, corpus_deleted, corpus_group, query_group,
           query_row, min_logit) -> (top_logit, top_index, count)

    ``count`` carries the same saturation semantics (above-bound
    candidates, widened by the int8 cosine-ambiguity credit) so the
    shared escalation loop drives nprobe/C growth.
    """
    import jax
    import jax.numpy as jnp

    from . import scoring as S

    pair_logits = S.build_gathered_pair_logits(plan)
    slot_chunk = scan_slots()

    @jax.jit
    def score(q_emb, qfeats, emb_tree, centroids, cell_rows, corpus_feats,
              corpus_valid, corpus_deleted, corpus_group, query_group,
              query_row, min_logit):
        if queries_from_rows:
            qrows = jnp.clip(query_row, 0)
            q_tree = {
                name: jnp.take(arr, qrows, axis=0)
                for name, arr in emb_tree.items()
            }
            qfeats = S.gather_rows(corpus_feats, qrows)
        else:
            q_tree = E.as_emb_tree(q_emb)
        top_sim, top_index = ivf_probe_topc(
            q_tree, emb_tree, centroids, cell_rows, corpus_valid,
            corpus_deleted, corpus_group, query_group, query_row,
            top_c=top_c, nprobe=nprobe, slot_chunk=slot_chunk,
            group_filtering=group_filtering,
        )
        return S.rescore_retrieved(
            pair_logits, qfeats, corpus_feats, top_sim, top_index,
            min_logit, amb_eps=S.retrieval_amb_eps(q_tree, emb_tree),
        )

    return score
