"""Shared-memory parallel feature extraction (VERDICT r4 #6).

Bulk ingest is host-bound Python glue: per-value string encoding, flat
list construction, gram/phonetic/class loops (ops.features).  The r4
parallel attempts documented WHY the obvious fan-outs lose: threads are
GIL-bound (the numpy/C bulk passes already release the GIL but no longer
dominate), and a process pool that returns tensors pays pickling + IPC
for ~1 KB/row both ways — 3-5x slower than serial.

This module keeps the process pool but deletes the expensive half of the
round trip: workers write their slice's feature tensors DIRECTLY into
``multiprocessing.shared_memory`` segments at their row offsets and
return nothing.  The input half (pickling the record slice in) is cheap
— records are a few hundred bytes of strings, ~5x smaller than their
extracted tensors.  Output shapes/dtypes are derived by running the
extractor on an EMPTY batch (no parallel re-implementation of the layout
to drift out of sync).

Workers are spawned (never forked: the parent holds live JAX/TPU runtime
threads) and import only numpy + the jax-free ops.features/ops.encoder
modules.  Env knobs (DEVICE_MAX_*) reach workers through inherited
environ, and the specs themselves ship per call, so auto-sized widths
are always current.

Enable: on by default for batches >= DEVICE_EXTRACT_PARALLEL_MIN (8192);
DEVICE_EXTRACT_WORKERS=0 disables.  Reference analog: the ingest fan-out
the reference gets from its servlet worker pool (App.java:231-236,344).
"""

from __future__ import annotations

import atexit
import os
import threading
from concurrent.futures import ProcessPoolExecutor
from multiprocessing import get_context, shared_memory
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..telemetry.env import env_int

_POOL: Optional[ProcessPoolExecutor] = None
_POOL_WORKERS = 0
# guards pool creation/replacement AND serializes map calls: two
# workloads extracting concurrently must not race the lazy init (leaked
# pool) or have a worker-count change terminate a pool mid-map
_POOL_LOCK = threading.Lock()


def workers() -> int:
    """Read at call time (not import) so tests/ops can retune live.  The
    default derives from the visible cores: on a single-core host (the
    bench environment here — nproc=1) every process pool loses by
    construction, exactly what the r4 measurements observed, so the
    pipeline self-disables; multi-core deployments get cores/2."""
    return env_int("DEVICE_EXTRACT_WORKERS", min(8, (os.cpu_count() or 1) // 2))


def min_records() -> int:
    """Smallest slab worth the process-pool fan-out.  Exported because the
    streaming-append slicer (engine.device_matcher) sizes its extract
    slices to at least this when the whole batch qualifies — slicing a
    bulk slab below it would silently forfeit the parallel path."""
    return env_int("DEVICE_EXTRACT_PARALLEL_MIN", 8192)


def enabled(n_records: int) -> bool:
    return workers() >= 2 and n_records >= min_records()


def _pool() -> ProcessPoolExecutor:
    """Call with _POOL_LOCK held.  ProcessPoolExecutor, not mp.Pool: a
    worker dying mid-task (OOM kill at slab scale) raises
    BrokenProcessPool from map() — which the caller's except clause
    turns into a serial fallback — where mp.Pool.map would block
    forever holding the workload lock."""
    global _POOL, _POOL_WORKERS
    w = workers()
    if _POOL is not None and _POOL_WORKERS != w:
        _shutdown_locked()
    if _POOL is None:
        _POOL = ProcessPoolExecutor(
            max_workers=w, mp_context=get_context("spawn"),
            initializer=_worker_init,
        )
        _POOL_WORKERS = w
        atexit.register(_shutdown)
    return _POOL


def _shutdown_locked() -> None:
    global _POOL
    if _POOL is not None:
        _POOL.shutdown(wait=False, cancel_futures=True)
        _POOL = None


def _shutdown() -> None:
    with _POOL_LOCK:
        _shutdown_locked()


def _worker_init() -> None:
    # workers never touch an accelerator; belt-and-braces in case a
    # transitive import ever reaches jax in a future refactor
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # dukecheck: ignore[DK301] spawned-worker env WRITE, not a knob read


def _worker_extract(task) -> None:
    """Extract one record slice into the shared segments.  Runs in a
    spawned worker; returns None — the tensors travel via shm."""
    specs, encoder, values_by_prop, lo, layout = task
    from . import features as F

    handles = []
    try:
        for spec in specs:
            out = F.extract_property(spec, values_by_prop[spec.name])
            for tname, arr in out.items():
                _write(layout[(spec.name, tname)], lo, arr, handles)
        if encoder is not None:
            records = _records_from_values(values_by_prop, encoder.props)
            emb = encoder.encode_batch(records).astype(np.float32)
            _write(layout[("__ann__", "emb_f32")], lo, emb, handles)
    finally:
        for shm in handles:
            shm.close()


def _write(entry, lo: int, arr: np.ndarray, handles: list) -> None:
    shm_name, shape, dtype = entry
    shm = shared_memory.SharedMemory(name=shm_name)
    handles.append(shm)
    view = np.ndarray(shape, dtype=np.dtype(dtype), buffer=shm.buf)
    view[lo:lo + arr.shape[0]] = arr


def _records_from_values(values_by_prop: Dict[str, List[List[str]]],
                         props: Sequence[str]):
    """Rebuild minimal Record stand-ins for the encoder (it only reads
    ``_values``), so records themselves never ride the task pickle twice."""
    from ..core.records import Record

    n = len(next(iter(values_by_prop.values())))
    out = []
    for i in range(n):
        r = Record.__new__(Record)
        r._values = {
            prop: values_by_prop[prop][i]
            for prop in props
            if prop in values_by_prop and values_by_prop[prop][i]
        }
        out.append(r)
    return out


def extract_batch_parallel(plan, records, *, encoder=None
                           ) -> Optional[Dict[str, Dict[str, np.ndarray]]]:
    """Shared-memory fan-out of ``features.extract_batch``; returns None
    when the pool is unavailable (caller falls back to serial)."""
    from . import encoder as E
    from . import features as F

    n = len(records)
    nw = max(1, workers())
    per = -(-n // nw)

    # the task payload: per-property value lists (strings), not Record
    # objects — smaller pickles and no Record internals in the wire format
    empty: List[str] = []
    prop_names = [s.name for s in plan.device_props]
    if encoder is not None:
        prop_names = sorted(set(prop_names) | set(encoder.props))
    values_by_prop = {
        name: [r._values.get(name, empty) for r in records]
        for name in prop_names
    }

    # output layout from the extractor itself on an empty batch
    layout: Dict[tuple, tuple] = {}
    segments: List[shared_memory.SharedMemory] = []

    def alloc(key, shape, dtype) -> None:
        nbytes = max(1, int(np.prod(shape)) * np.dtype(dtype).itemsize)
        shm = shared_memory.SharedMemory(create=True, size=nbytes)
        segments.append(shm)
        layout[key] = (shm.name, shape, str(np.dtype(dtype)))

    try:
        try:
            for spec in plan.device_props:
                proto = F.extract_property(spec, [])
                for tname, arr in proto.items():
                    alloc((spec.name, tname), (n,) + arr.shape[1:],
                          arr.dtype)
            if encoder is not None:
                alloc(("__ann__", "emb_f32"), (n, encoder.dim), np.float32)
        except OSError:
            # /dev/shm too small for the slab (Docker defaults to 64 MB)
            # — the contract is a transparent serial fallback, never a
            # failed ingest request
            import logging

            logging.getLogger("parallel-extract").exception(
                "shared-memory allocation failed; falling back to serial"
            )
            return None

        tasks = []
        for w in range(nw):
            lo, hi = w * per, min(n, (w + 1) * per)
            if lo >= hi:
                break
            slice_values = {
                name: vals[lo:hi] for name, vals in values_by_prop.items()
            }
            tasks.append((plan.device_props, encoder, slice_values, lo,
                          layout))
        try:
            with _POOL_LOCK:
                # list() drains the generator so worker exceptions
                # (including BrokenProcessPool from a dead worker)
                # surface HERE, inside the fallback guard
                list(_pool().map(_worker_extract, tasks))
        except Exception:
            import logging

            logging.getLogger("parallel-extract").exception(
                "shared-memory extraction failed; falling back to serial"
            )
            _shutdown()
            return None

        out: Dict[str, Dict[str, np.ndarray]] = {}
        for spec in plan.device_props:
            out[spec.name] = {}
        for (prop, tname), (shm_name, shape, dtype) in layout.items():
            shm = next(s for s in segments if s.name == shm_name)
            view = np.ndarray(shape, dtype=np.dtype(dtype), buffer=shm.buf)
            if (prop, tname) == ("__ann__", "emb_f32"):
                # parent-side storage conversion (workers always emit
                # f32): bf16 cast, or int8 quantization + scale vector
                # under DUKE_EMB_INT8 — the ONE conversion point shared
                # with the serial path (ops.encoder.corpus_tensors_from_f32)
                out[E.ANN_PROP] = E.corpus_tensors_from_f32(
                    view, encoder.storage
                )
            else:
                out[prop][tname] = view.copy()
        return out
    finally:
        for shm in segments:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
