"""Digest-keyed feature-row cache: make re-encode of unchanged records free.

A Sesam full resync — the reference's normal sync mode — re-POSTs entire
datasets of mostly-unchanged entities.  The corpus is append-only with
digest-tracked re-upserts (engine.device_matcher), so the *index* side of a
re-upsert is cheap (tombstone + append), but every appended row re-runs
per-record host feature extraction (ops.features.extract_batch) even when
the record's bytes did not change.  After PR 3 removed the post-device
finalization bottleneck, that re-extraction is the serial segment bounding
steady-state ingest.

This module caches extracted feature ROWS keyed by

    (record content digest, feature-plan fingerprint)

where the digest is the store's canonical per-record digest
(``store.records.record_digest`` — the exact bytes the durable store folds,
so a cache hit is guaranteed to describe the same record content) and the
fingerprint covers everything that shapes or parameterizes extraction:
per-property kind, value-slot width, char width, comparator class (and its
``q``), the global gram/token paddings, the char-tensor dtype, and the ANN
encoder (dim, props, storage dtype) when one rides along.  Value-slot
widening, char-width growth, long-text demotion, and schema changes all
change the fingerprint, so stale rows can never be scattered into a
corpus built under a different plan — the cache is self-invalidating, no
explicit flush hooks anywhere.

Budget: ``DUKE_FEATURE_CACHE_MB`` (default 256; ``0`` disables) bounds the
cached tensor bytes with LRU eviction.  One row is ~1 KB for a typical
schema, so the default holds a few hundred thousand hot rows.

Consumers: ``ops.features.extract_batch`` consults the cache for every
batch — corpus appends, config-reload / plan-change rebuilds, and
query-side extraction (http-transform probes, follower score replay) all
share that one entry point, so they all hit when their plan matches the
plan the rows were cached under.  ``engine.device_matcher.snapshot_load``
pre-warms the cache from the restored corpus tensors so the FIRST resync
after a restart is already warm.

Thread safety: one lock around the LRU map.  The workload lock serializes
the ingest path, but the scorer pre-warm thread extracts dummy records and
the restart warm path runs outside it, so the cache must not rely on it.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry.env import env_int

DEFAULT_MB = 256

# per-entry bookkeeping overhead (key bytes, dict-of-dict structure) added
# to the tensor bytes so the budget tracks real memory, not just payload
_ENTRY_OVERHEAD = 256

RowDict = Dict[str, Dict[str, np.ndarray]]


class FeatureCache:
    """Byte-budgeted LRU of extracted feature rows."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._rows: "collections.OrderedDict[tuple, Tuple[RowDict, int]]" = (
            collections.OrderedDict()  # guarded by: self._lock
        )
        self.bytes = 0  # guarded by: self._lock [writes]
        # monotonic, single-writer-per-increment under self._lock; scraped
        # lock-free by the /metrics process collector (torn reads of a
        # plain int are fine for visibility counters)
        self.hits = 0  # guarded by: self._lock [writes]
        self.misses = 0  # guarded by: self._lock [writes]
        self.evicted = 0  # guarded by: self._lock [writes]

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def get_many(self, fp, digests: Sequence[Optional[bytes]]
                 ) -> Dict[int, RowDict]:
        """Look up a batch; returns ``{batch_index: row}`` for the hits.

        ``None`` digests (records without an ID, foreign record-likes)
        always miss and are counted as misses — they are rows the cache
        cannot help with, which is exactly what the hit ratio should say.
        """
        out: Dict[int, RowDict] = {}
        with self._lock:
            for i, digest in enumerate(digests):
                if digest is None:
                    continue
                entry = self._rows.get((fp, digest))
                if entry is not None:
                    self._rows.move_to_end((fp, digest))
                    out[i] = entry[0]
            self.hits += len(out)
            self.misses += len(digests) - len(out)
        return out

    def put_many(self, fp, items: Iterable[Tuple[bytes, RowDict]]) -> None:
        """Insert freshly extracted rows; evicts LRU past the byte budget."""
        with self._lock:
            for digest, row in items:
                nbytes = _ENTRY_OVERHEAD + sum(
                    arr.nbytes for tensors in row.values()
                    for arr in tensors.values()
                )
                if nbytes > self.budget_bytes:
                    continue  # a single over-budget row would only thrash
                key = (fp, digest)
                old = self._rows.pop(key, None)
                if old is not None:
                    self.bytes -= old[1]
                self._rows[key] = (row, nbytes)
                self.bytes += nbytes
            while self.bytes > self.budget_bytes and self._rows:
                _, (_, nbytes) = self._rows.popitem(last=False)
                self.bytes -= nbytes
                self.evicted += 1

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self.bytes = 0


_CACHE: Optional[FeatureCache] = None
_CACHE_MB: Optional[int] = None
_CACHE_LOCK = threading.Lock()


def budget_mb() -> int:
    return env_int("DUKE_FEATURE_CACHE_MB", DEFAULT_MB)


def active() -> Optional[FeatureCache]:
    """The process-wide cache, or None when disabled.  Re-reads the env
    budget on every call (cheap) so tests can flip it live; a budget
    change replaces the cache (operators never change env mid-process)."""
    global _CACHE, _CACHE_MB
    mb = budget_mb()
    if mb <= 0:
        return None
    with _CACHE_LOCK:
        if _CACHE is None or _CACHE_MB != mb:
            _CACHE = FeatureCache(mb << 20)
            _CACHE_MB = mb
        return _CACHE


def reset() -> None:
    """Drop the process-wide cache (tests)."""
    global _CACHE, _CACHE_MB
    with _CACHE_LOCK:
        _CACHE = None
        _CACHE_MB = None


def stats() -> Tuple[int, int, int, int]:
    """(hits, misses, evicted_rows, bytes) of the active cache — zeros when
    disabled.  Lock-free snapshot reads (scrape path must never block)."""
    cache = _CACHE if budget_mb() > 0 else None
    if cache is None:
        return (0, 0, 0, 0)
    return (cache.hits, cache.misses, cache.evicted, cache.bytes)


# -- keys ---------------------------------------------------------------------


def plan_fingerprint(plan, encoder=None) -> tuple:
    """Everything that parameterizes extraction for ``plan``.

    Deliberately EXCLUDES low/high probability bounds: they shape scoring,
    not the extracted tensors, so a config reload that only retunes
    thresholds re-uses every cached row.  Includes the comparator class
    name (PHONETIC covers Soundex/Metaphone/Norphone, which extract
    different codes) and QGram's ``q``.

    The encoder leg carries the resolved embedding storage mode (bf16 vs
    the DUKE_EMB_INT8 per-row symmetric int8 + scale layout) and the
    DUKE_IVF retrieval mode — mirroring the ``emb_storage`` snapshot
    guard (engine.ann_matcher) in the cache key, so a dtype or IVF flip
    between restarts self-invalidates cached rows instead of scattering
    one storage layout into a corpus built under the other.
    """
    from . import features as F

    specs = tuple(
        (s.name, s.kind, int(s.values_per_record), int(s.chars),
         type(s.comparator).__name__, getattr(s.comparator, "q", None))
        for s in plan.device_props
    )
    enc = None
    if encoder is not None:
        from . import encoder as E
        from . import ivf

        enc = (int(encoder.dim), tuple(encoder.props),
               getattr(encoder, "storage", None) or E.storage_name(),
               bool(ivf.enabled()))
    return (specs, F.MAX_GRAMS, F.MAX_TOKENS,
            str(np.dtype(F.CHAR_DTYPE)), enc)


def record_key(record) -> Optional[bytes]:
    """Canonical content digest for ``record``, or None when the record
    cannot be keyed (no ID / foreign record-like) — such rows extract
    directly and are never cached."""
    from ..store.records import record_digest

    try:
        if record.record_id is None:
            return None
        return record_digest(record)
    except (AttributeError, ValueError, TypeError):
        return None


# -- batch assembly -----------------------------------------------------------


def _row_slice(feats: RowDict, j: int) -> RowDict:
    """Copy row ``j`` out of batch tensors (a view would pin the whole
    batch's memory and break the byte accounting).

    The trailing ``reshape`` pins the cached row to exactly the batch
    tensor's per-row shape: ``np.ascontiguousarray`` promotes 0-d slices
    to ``(1,)``, which would make rows of 1-D per-row tensors (the int8
    embedding scale) scatter back with a phantom axis on the all-hit
    path and silently produce ``(n, 1)`` where misses produce ``(n,)``.
    """
    return {
        prop: {name: np.ascontiguousarray(arr[j]).reshape(arr.shape[1:])
               for name, arr in tensors.items()}
        for prop, tensors in feats.items()
    }


def cached_extract(cache: FeatureCache, plan, records, *,
                   encoder=None) -> RowDict:
    """``features.extract_batch`` semantics through the cache: hits scatter
    from cached rows, misses extract through the normal path (including
    the shared-memory parallel fan-out when the miss slab qualifies) and
    are inserted for the next sync."""
    from . import features as F

    if not records:
        return F._extract_direct(plan, records, encoder=encoder)
    n = len(records)
    fp = plan_fingerprint(plan, encoder)
    keys = [record_key(r) for r in records]
    hits = cache.get_many(fp, keys)
    miss_idx = [i for i in range(n) if i not in hits]

    miss_out = None
    if miss_idx:
        miss_out = F._extract_direct(
            plan, [records[i] for i in miss_idx], encoder=encoder
        )

    if not hits:
        out = miss_out  # no hits and records non-empty => all missed
    else:
        # output shapes/dtypes from the miss extraction when there is one
        # (authoritative for this plan), else from any cached row (same
        # fingerprint => same layout by construction)
        if miss_out is not None:
            shapes = {
                (prop, name): (arr.shape[1:], arr.dtype)
                for prop, tensors in miss_out.items()
                for name, arr in tensors.items()
            }
        else:
            proto = hits[next(iter(hits))]
            shapes = {
                (prop, name): (arr.shape, arr.dtype)
                for prop, tensors in proto.items()
                for name, arr in tensors.items()
            }
        out = {}
        for (prop, name), (shape, dtype) in shapes.items():
            out.setdefault(prop, {})[name] = np.zeros(
                (n,) + shape, dtype=dtype
            )
        hit_idx = np.fromiter(sorted(hits), dtype=np.int64, count=len(hits))
        miss_arr = np.asarray(miss_idx, dtype=np.int64)
        for (prop, name) in shapes:
            dst = out[prop][name]
            if miss_out is not None and miss_arr.size:
                dst[miss_arr] = miss_out[prop][name]
            if hit_idx.size:
                dst[hit_idx] = np.stack(
                    [hits[int(i)][prop][name] for i in hit_idx]
                )

    if miss_out is not None:
        cache.put_many(fp, (
            (keys[i], _row_slice(miss_out, j))
            for j, i in enumerate(miss_idx)
            if keys[i] is not None
        ))
    return out


# -- restart pre-warm ---------------------------------------------------------


def prewarm(plan, encoder, feats: RowDict, id_to_row: Dict[str, int],
            digest_iter: Iterable[Tuple[str, bytes]],
            cache: FeatureCache) -> int:
    """Seed the cache from restored corpus tensors (snapshot load).

    ``digest_iter`` yields (record_id, canonical digest) — from the
    durable store's raw rows (``RecordStore.row_digests``), so no record
    decode happens here.  Stops at the byte budget: a 10M-row corpus
    warms only as many rows as the cache could ever hold anyway.
    Returns the number of rows warmed.
    """
    fp = plan_fingerprint(plan, encoder)
    warmed = 0
    batch: List[Tuple[bytes, RowDict]] = []
    for rid, digest in digest_iter:
        if cache.bytes >= cache.budget_bytes:
            break
        row = id_to_row.get(rid)
        if row is None:
            continue
        batch.append((digest, _row_slice(feats, row)))
        warmed += 1
        if len(batch) >= 1024:
            cache.put_many(fp, batch)
            batch = []
    if batch:
        cache.put_many(fp, batch)
    return warmed
