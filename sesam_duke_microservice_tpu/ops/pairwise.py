"""Batched pairwise similarity kernels (JAX, TPU-friendly).

Every kernel maps a flat batch of P value pairs to similarities in [0, 1],
replicating the scalar semantics of ``core.comparators`` (the oracles; each
kernel has a differential test against them).  TPU-first design notes:

  * All shapes are static; the pair batch is the parallel axis the VPU works
    over.  No per-pair Python, no data-dependent shapes.
  * Edit distance avoids the sequential inner loop with the classic
    min-plus-scan identity::

        cur[j] = min(prev[j]+1, prev[j-1]+cost[j], cur[j-1]+1)
               = j + cummin( m[k] - k )[j],   m[k] = min(prev[k]+1, prev[k-1]+cost[k])

    so each DP row is one vectorized ``associative_scan`` over the column
    axis; ``lax.scan`` walks rows.  O(L) steps of O(P*L) vector work instead
    of O(P*L^2) scalar work — the same wavefront idea a systolic algorithm
    uses, expressed in XLA ops.
  * Set intersections (q-grams, tokens) use a dense all-pairs equality
    compare: O(P*S^2) fully-vectorized VPU work with zero gathers.  The
    asymptotically better binary search loses by ~400x on TPU because its
    per-row ``take_along_axis`` steps lower to serialized dynamic gathers
    (see ``set_intersection_count``).
  * Jaro's greedy char matching is inherently sequential in the query string;
    we scan its <=L steps with all pairs advancing in lockstep, each step
    fully vectorized over P and the candidate axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

INT32_MAX = 2**31 - 1


# -- edit distance -----------------------------------------------------------


def levenshtein_distance_myers(c1, l1, c2, l2):
    """Batched Levenshtein distance via Myers' bit-parallel algorithm.

    Requires L <= 32 (pattern bits live in one uint32 word).  Each scan step
    is ~15 elementwise uint32 ops on (P,) vectors — ideal TPU layout (pairs
    on lanes, no wide minor axis, no gathers) and ~10x less work than the
    min-plus scan DP.  Hyyro's formulation: pattern = c1 (row bits), text =
    c2 (scan steps); score tracks cell (l1, i) and finishes at i = l2.

    c1, c2: (P, L) int32 codepoints (0-padded); l1, l2: (P,) int32 lengths.
    Returns (P,) int32 distances d(c1[:l1], c2[:l2]).
    """
    p, l = c1.shape
    if l > 32:
        raise ValueError(f"Myers kernel needs L <= 32, got {l}")
    c1t = c1.T  # (L, P): pairs on the lane (minor) axis
    c2t = c2.T
    one = jnp.uint32(1)
    l1u = l1.astype(jnp.uint32)
    # bit j set iff j < l1 (l1 <= 32; guard the undefined <<32)
    full = jnp.uint32(0xFFFFFFFF)
    pv0 = jnp.where(
        l1u >= 32, full, (one << jnp.minimum(l1u, jnp.uint32(31))) - one
    )
    hibit = one << (jnp.maximum(l1u, one) - one)
    shifts = jnp.arange(l, dtype=jnp.uint32)[:, None]  # (L, 1)

    def step(carry, i):
        pv, mv, score = carry
        tc = lax.dynamic_slice_in_dim(c2t, i, 1, axis=0)       # (1, P)
        eqbits = (c1t == tc).astype(jnp.uint32) << shifts      # (L, P)
        eq = eqbits.sum(axis=0)  # bits are disjoint: sum == OR  (P,)
        xv = eq | mv
        xh = (((eq & pv) + pv) ^ pv) | eq
        ph = mv | ~(xh | pv)
        mh = pv & xh
        active = i < l2
        score = score + jnp.where(active & ((ph & hibit) != 0), 1, 0)
        score = score - jnp.where(active & ((mh & hibit) != 0), 1, 0)
        ph = (ph << one) | one
        mh = mh << one
        pv_new = mh | ~(xv | ph)
        mv_new = ph & xv
        pv = jnp.where(active, pv_new, pv)
        mv = jnp.where(active, mv_new, mv)
        return (pv, mv, score), None

    (pv, mv, score), _ = lax.scan(
        step,
        (pv0, jnp.zeros((p,), jnp.uint32), l1.astype(jnp.int32)),
        jnp.arange(l, dtype=jnp.int32),
    )
    # empty pattern: distance is the text length
    return jnp.where(l1 == 0, l2, score)


def levenshtein_distance(c1, l1, c2, l2):
    """Batched Levenshtein distance.

    c1, c2: (P, L) int32 codepoints (0-padded); l1, l2: (P,) int32 lengths.
    Returns (P,) int32 distances d(c1[:l1], c2[:l2]).
    """
    p, l = c1.shape
    jidx = jnp.arange(l + 1, dtype=jnp.int32)
    init_row = jnp.broadcast_to(jidx, (p, l + 1))
    init_result = l2  # distance when l1 == 0

    def step(carry, i):
        prev, result = carry
        ch = lax.dynamic_slice_in_dim(c1, i, 1, axis=1)  # (P, 1)
        cost = jnp.where(c2 == ch, 0, 1)  # (P, L)
        m = jnp.minimum(prev[:, 1:] + 1, prev[:, :-1] + cost)
        row0 = jnp.full((p, 1), i + 1, dtype=jnp.int32)
        g = jnp.concatenate([row0, m], axis=1) - jidx
        cur = lax.associative_scan(jnp.minimum, g, axis=1) + jidx
        d = jnp.take_along_axis(cur, l2[:, None], axis=1)[:, 0]
        result = jnp.where(i + 1 == l1, d, result)
        return (cur, result), None

    (_, result), _ = lax.scan(
        step, (init_row, init_result), jnp.arange(l, dtype=jnp.int32)
    )
    return result


def levenshtein_sim_from_distance(dist, l1, l2, equal):
    """Duke's distance -> similarity map (core.comparators.Levenshtein).

    Shared by the flat XLA path below and the Pallas tiled path
    (ops.pallas_kernels) so the two scoring paths cannot desync; operands
    broadcast, so (P,) and (Q, 1) x (1, C) shapes both work.
    """
    shorter = jnp.minimum(l1, l2)
    longer = jnp.maximum(l1, l2)
    dist = jnp.minimum(dist, shorter)
    sim = 1.0 - dist.astype(jnp.float32) / jnp.maximum(shorter, 1).astype(jnp.float32)
    sim = jnp.where((longer - shorter) * 2 > shorter, 0.0, sim)
    sim = jnp.where(shorter == 0, 0.0, sim)
    return jnp.where(equal, 1.0, sim)


def levenshtein_sim(c1, l1, c2, l2, equal):
    """Duke Levenshtein similarity (core.comparators.Levenshtein.compare).

    ``equal``: (P,) bool — exact string equality (from value hashes), the
    comparators' shared v1==v2 early exit.
    """
    if c1.shape[1] <= 32:
        dist = levenshtein_distance_myers(c1, l1, c2, l2)
    else:
        dist = levenshtein_distance(c1, l1, c2, l2)
    return levenshtein_sim_from_distance(dist, l1, l2, equal)


def weighted_levenshtein_sim(
    c1, k1, l1, c2, k2, l2, equal, *, digit_weight, letter_weight, other_weight
):
    """core.comparators.WeightedLevenshtein.compare.

    k1, k2: (P, L) int32 char classes (0 other, 1 letter, 2 digit) computed
    on host with Python's unicode str.isalpha/isdigit for oracle parity.
    """
    p, l = c1.shape
    wvec = jnp.array([other_weight, letter_weight, digit_weight], jnp.float32)
    w1 = jnp.take(wvec, k1)  # (P, L)
    w2 = jnp.take(wvec, k2)
    cw2 = jnp.cumsum(w2, axis=1)
    zeros = jnp.zeros((p, 1), jnp.float32)
    prefix2 = jnp.concatenate([zeros, cw2], axis=1)  # (P, L+1) = row 0

    def step(carry, i):
        prev, row0_prev, result = carry
        ch = lax.dynamic_slice_in_dim(c1, i, 1, axis=1)
        wi = lax.dynamic_slice_in_dim(w1, i, 1, axis=1)  # (P, 1)
        sub = jnp.where(c2 == ch, 0.0, jnp.maximum(wi, w2))
        m = jnp.minimum(prev[:, 1:] + wi, prev[:, :-1] + sub)
        row0 = row0_prev + wi[:, 0]
        g = jnp.concatenate([row0[:, None], m], axis=1) - prefix2
        cur = lax.associative_scan(jnp.minimum, g, axis=1) + prefix2
        d = jnp.take_along_axis(cur, l2[:, None], axis=1)[:, 0]
        result = jnp.where(i + 1 == l1, d, result)
        return (cur, row0, result), None

    init_result = jnp.take_along_axis(prefix2, l2[:, None], axis=1)[:, 0]
    (_, _, result), _ = lax.scan(
        step,
        (prefix2, jnp.zeros((p,), jnp.float32), init_result),
        jnp.arange(l, dtype=jnp.int32),
    )
    shorter = jnp.minimum(l1, l2).astype(jnp.float32)
    dist = jnp.minimum(result, shorter)
    sim = 1.0 - dist / jnp.maximum(shorter, 1.0)
    sim = jnp.where(shorter == 0, 0.0, sim)
    return jnp.where(equal, 1.0, sim)


# -- Jaro-Winkler ------------------------------------------------------------


def jaro_counts(c1, l1, c2, l2):
    """The integer core of Jaro: (matches, transpositions) as exact int32.

    Exposed for the certified dd rescore (ops.scoring): the Jaro-Winkler
    similarity is a rational function of these counts plus the lengths
    and the common-prefix length, so the double-double pipeline only
    needs the counts — the float math is redone in dd.
    """
    p, l = c1.shape
    jidx = jnp.arange(l, dtype=jnp.int32)
    window = jnp.maximum(jnp.maximum(l1, l2) // 2 - 1, 0)  # (P,)

    def step(carry, i):
        used, nmatch, m1 = carry
        ch = lax.dynamic_slice_in_dim(c1, i, 1, axis=1)  # (P, 1)
        lo = jnp.maximum(0, i - window)[:, None]
        hi = jnp.minimum(l2, i + window + 1)[:, None]
        ok = (
            (~used)
            & (c2 == ch)
            & (jidx >= lo)
            & (jidx < hi)
            & (i < l1)[:, None]
        )
        any_ok = ok.any(axis=1)
        first = jnp.argmax(ok, axis=1)
        used = used | (ok & (jidx == first[:, None]))
        m1 = jnp.where(
            (jidx == nmatch[:, None]) & any_ok[:, None], ch, m1
        )
        nmatch = nmatch + any_ok.astype(jnp.int32)
        return (used, nmatch, m1), None

    used0 = jnp.zeros((p, l), bool)
    nmatch0 = jnp.zeros((p,), jnp.int32)
    m10 = jnp.zeros((p, l), jnp.int32)
    (used, nmatch, m1), _ = lax.scan(
        step, (used0, nmatch0, m10), jnp.arange(l, dtype=jnp.int32)
    )

    # compact matched chars of c2 in order: scatter c2[j] to rank position
    rank = jnp.cumsum(used.astype(jnp.int32), axis=1) - 1
    pos = jnp.where(used, rank, l)  # l = out of range -> dropped
    pidx = jnp.arange(p)[:, None]
    m2 = jnp.zeros((p, l), jnp.int32).at[pidx, pos].set(c2, mode="drop")

    kidx = jnp.arange(l, dtype=jnp.int32)
    diff = (m1 != m2) & (kidx < nmatch[:, None])
    transpositions = diff.sum(axis=1) // 2
    return nmatch, transpositions.astype(jnp.int32)


def _jaro(c1, l1, c2, l2):
    nmatch, transpositions = jaro_counts(c1, l1, c2, l2)
    m = nmatch.astype(jnp.float32)
    n1 = jnp.maximum(l1, 1).astype(jnp.float32)
    n2 = jnp.maximum(l2, 1).astype(jnp.float32)
    jaro = (m / n1 + m / n2 + (m - transpositions) / jnp.maximum(m, 1.0)) / 3.0
    return jnp.where((nmatch == 0) | (l1 == 0) | (l2 == 0), 0.0, jaro)


def common_prefix_count(c1, c2, l1, l2, *, max_prefix):
    """Winkler common-prefix length (exact int32, capped at max_prefix)."""
    l = c1.shape[1]
    k = min(int(max_prefix), l)
    kidx = jnp.arange(k, dtype=jnp.int32)
    both = jnp.minimum(l1, l2)[:, None]
    eq = (c1[:, :k] == c2[:, :k]) & (kidx < both)
    return jnp.cumprod(eq.astype(jnp.int32), axis=1).sum(axis=1)


def jaro_winkler_sim(
    c1, l1, c2, l2, equal, *, prefix_scale=0.1, boost_threshold=0.7, max_prefix=4
):
    """core.comparators.JaroWinkler.compare."""
    j = _jaro(c1, l1, c2, l2)
    prefix = common_prefix_count(c1, c2, l1, l2, max_prefix=max_prefix)
    boosted = j + prefix.astype(jnp.float32) * prefix_scale * (1.0 - j)
    sim = jnp.where(j < boost_threshold, j, boosted)
    return jnp.where(equal, 1.0, sim)


# -- sorted-set intersection -------------------------------------------------


def set_intersection_count(a, na, b, nb):
    """|set(a[:na]) ∩ set(b[:nb])| for distinct int32 ids.

    a: (P, Sa), b: (P, Sb), padded with INT32_MAX.

    Dense all-pairs equality compare + reduce: O(Sa*Sb) elementwise work,
    fully vectorized on the VPU with zero gathers.  The asymptotically
    better batched binary search (O(Sa log Sb)) loses by ~400x on TPU
    because its per-row ``take_along_axis`` steps lower to serialized
    dynamic gathers along the minor dimension — measured 10.5 s vs 26 ms
    per 2M-pair scoring call on v5e.  Elements are distinct within each
    set, so counting equal (i, j) combinations counts the intersection.
    """
    sa = a.shape[1]
    sb = b.shape[1]
    valid_a = jnp.arange(sa, dtype=jnp.int32) < na[:, None]      # (P, Sa)
    valid_b = jnp.arange(sb, dtype=jnp.int32) < nb[:, None]      # (P, Sb)
    eq = a[:, :, None] == b[:, None, :]                          # (P, Sa, Sb)
    hits = eq & valid_a[:, :, None] & valid_b[:, None, :]
    return hits.sum(axis=(1, 2)).astype(jnp.int32)


def sim_from_set_intersection(common, f1, f2, equal, *, formula):
    """Shared |A ∩ B| -> similarity map for every set comparator.

    One copy of the overlap/jaccard/dice math (plus the empty-set zero and
    exact-equality override) used by both the flat kernels here and the
    Pallas tile kernels — operands broadcast, so (P,) and (Q,1)x(1,C)
    shapes both work.  QGram uses all three formulas; JaccardIndex ≡
    'jaccard'; DiceCoefficient ≡ 'dice' (core.comparators semantics).
    """
    common = common.astype(jnp.float32)
    f1 = f1.astype(jnp.float32)
    f2 = f2.astype(jnp.float32)
    if formula == "jaccard":
        sim = common / jnp.maximum(f1 + f2 - common, 1.0)
    elif formula == "dice":
        sim = 2.0 * common / jnp.maximum(f1 + f2, 1.0)
    else:
        sim = common / jnp.maximum(jnp.minimum(f1, f2), 1.0)
    sim = jnp.where((f1 == 0) | (f2 == 0), 0.0, sim)
    return jnp.where(equal, 1.0, sim)


def qgram_sim(g1, n1, g2, n2, equal, *, formula="overlap"):
    """core.comparators.QGram.compare over precomputed distinct-gram sets."""
    common = set_intersection_count(g1, n1, g2, n2)
    return sim_from_set_intersection(common, n1, n2, equal, formula=formula)


def token_set_sim(t1, n1, t2, n2, equal, *, dice=False):
    """JaccardIndex (dice=False) / DiceCoefficient (dice=True) over token sets."""
    inter = set_intersection_count(t1, n1, t2, n2)
    return sim_from_set_intersection(
        inter, n1, n2, equal, formula="dice" if dice else "jaccard"
    )


# -- scalar comparators ------------------------------------------------------


def exact_sim(equal):
    return jnp.where(equal, 1.0, 0.0)


def different_sim(equal):
    return jnp.where(equal, 0.0, 1.0)


def phonetic_sim(equal, code_equal, codes_valid):
    """Soundex/Metaphone/Norphone: equal values 1.0, equal nonempty codes 0.9."""
    return jnp.where(equal, 1.0, jnp.where(code_equal & codes_valid, 0.9, 0.0))


def numeric_sim(d1, v1, d2, v2, *, min_ratio=0.0):
    """core.comparators.Numeric.compare (note: NO string-equality early exit —
    two equal unparseable strings are neutral 0.5, matching the oracle)."""
    both = v1 & v2
    neutral = jnp.float32(0.5)
    a1 = jnp.abs(d1)
    a2 = jnp.abs(d2)
    ratio = jnp.minimum(a1, a2) / jnp.maximum(jnp.maximum(a1, a2), 1e-38)
    sim = jnp.where(ratio < min_ratio, 0.0, ratio)
    zero_or_sign = (d1 == 0.0) | (d2 == 0.0) | ((d1 < 0.0) != (d2 < 0.0))
    sim = jnp.where(zero_or_sign, 0.0, sim)
    sim = jnp.where(d1 == d2, 1.0, sim)
    return jnp.where(both, sim, neutral)


_EARTH_RADIUS_M = 6371000.0


def geoposition_sim(lat1, lon1, v1, lat2, lon2, v2, *, max_distance=0.0):
    """core.comparators.Geoposition.compare (haversine; radians precomputed)."""
    both = v1 & v2
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = (
        jnp.sin(dlat / 2) ** 2
        + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2) ** 2
    )
    dist = 2.0 * _EARTH_RADIUS_M * jnp.arcsin(jnp.minimum(1.0, jnp.sqrt(a)))
    if max_distance <= 0:
        sim = jnp.where(dist == 0.0, 1.0, 0.0)
    else:
        sim = jnp.maximum(0.0, 1.0 - dist / max_distance)
    return jnp.where(both, sim, jnp.float32(0.5))
