"""Hashed character-n-gram record embeddings for ANN candidate blocking.

The embedding-ANN backend (``engine.ann_matcher``) replaces exhaustive
brute-force blocking with a two-stage program: a cosine top-C retrieval over
a dense embedding matrix (one bf16 matmul per corpus chunk — pure MXU work),
followed by exact rescoring of only the retrieved candidates.  This is the
TPU-native counterpart of the reference's Lucene token blocking
(IncrementalLuceneDatabase.java:459-492): where Lucene ORs analyzed tokens
into a BooleanQuery and scores tf-idf overlap, we hash character n-grams of
every comparison property into a signed D-dimensional feature vector
(Weinberger et al.'s hashing trick) and let cosine similarity rank the
corpus.  Character n-grams — not word tokens — so the blocking stage is
robust to exactly the typo classes the comparators (Levenshtein,
Jaro-Winkler, q-gram) are configured to tolerate.

Encoding runs on host (numpy scatter-add; O(len) per record, once per
ingest) because it is tiny next to retrieval; retrieval runs on device where
the corpus-sized work is.  No learned weights, no external model downloads —
the encoder is deterministic from the schema alone.
"""

from __future__ import annotations

import math
from typing import Dict, List, Sequence

import ml_dtypes
import numpy as np

from ..core.records import Record
from ..telemetry.env import env_flag, env_float, env_int
from . import features as F

# Pseudo-property under which the corpus embedding matrix rides inside the
# DeviceCorpus feature tree (so growth/upload/incremental-update machinery in
# engine.device_matcher applies to it unchanged).
ANN_PROP = "__ann__"
ANN_TENSOR = "emb"
# int8 storage mode only (DUKE_EMB_INT8): the per-row symmetric
# quantization scale rides the corpus tree as a second ANN_PROP tensor,
# so append/growth/tombstone/snapshot machinery covers it for free.
ANN_SCALE = "scale"

# Storage dtype for the corpus embedding matrix — THE single decision
# point (ann_matcher, the sharded bench, the driver dryrun, and the
# sharded tests all take it from here).  bf16: retrieval casts both
# matmul operands to bf16 for the MXU anyway, so denser storage halves
# the dominant HBM/row term and the scan's memory traffic at identical
# blocking quality (candidates are rescored exactly either way).
#
# DUKE_EMB_INT8=1 halves it AGAIN: rows are stored as symmetric per-row
# int8 (q = round(v * 127 / max|v|), scale = max|v| / 127 riding as the
# ANN_SCALE tensor) and retrieval runs an int8 x int8 -> int32 MXU
# matmul rescaled by the two row scales.  The int32 accumulation is
# EXACT (D * 127^2 << 2^31 for any dim up to ~130k), so the only
# retrieval error is the vector quantization itself, bounded by
# ``int8_cosine_eps`` and credited to the recall-escalation trigger
# (ops.scoring.build_ann_scorer) instead of silently eating recall.
STORAGE_DTYPE = ml_dtypes.bfloat16


def int8_enabled() -> bool:
    """int8 embedding storage toggle (read at encoder construction so one
    index can never mix dtypes mid-life; the snapshot fingerprint and the
    feature-cache plan fingerprint both carry the resolved mode)."""
    return env_flag("DUKE_EMB_INT8", False)


def storage_name(storage: str = None) -> str:
    """Canonical storage-mode string (snapshot + cache fingerprints)."""
    if storage is not None:
        return storage
    return "int8" if int8_enabled() else str(np.dtype(STORAGE_DTYPE))


def int8_cosine_eps(dim: int) -> float:
    """Certified worst-case |exact cosine - int8-reconstructed cosine|.

    Rows are L2-normalized before quantization, so per component the
    reconstruction error is at most scale/2 with scale = max|v|/127 <= 1/127,
    giving a per-vector L2 error of at most sqrt(D)/254.  With
    q = v + dq, c = v' + dc (||v|| = ||v'|| = 1):

        |q.c - v.v'| <= ||dq|| + ||dc|| + ||dq||*||dc||
                     <= 2*sqrt(D)/254 + D/254^2

    The int32 dot of the stored int8 codes is exact (D * 127^2 < 2^31),
    so this bound covers the WHOLE retrieval-score error.  Used to widen
    the recall-escalation trigger: retrieved candidates within 2*eps of
    the top-C cutoff could be displaced by quantization, so they are
    counted as saturation evidence (ops.scoring.build_ann_scorer).
    """
    per_side = math.sqrt(float(dim)) / 254.0
    return 2.0 * per_side + per_side * per_side


def int8_cosine_eps_dynamic(q_tree: Dict, c_tree: Dict):
    """Traced per-block certified cosine-error bound from the ACTUAL
    row scales: ``sqrt(D)/2 * (sq + sc) + D/4 * sq * sc`` with sq/sc the
    max query/corpus scale in the block.

    Same derivation as ``int8_cosine_eps`` (which substitutes the
    worst-possible scale 1/127) — hashed-n-gram rows have max components
    well below 1, so the actual scales are typically ~4x smaller and the
    bound ~4x tighter while staying a deterministic worst case.  The
    static bound made the escalation credit fire routinely on flat
    cosine tails (a ~0.26 band at dim 256); this one keeps the credit a
    rare-saturation signal.  Returns a jnp scalar (trace-safe).
    """
    import jax.numpy as jnp

    d = float(q_tree[ANN_TENSOR].shape[-1])
    root = math.sqrt(d) / 2.0
    sq = jnp.max(q_tree[ANN_SCALE])
    sc = jnp.max(c_tree[ANN_SCALE])
    return root * (sq + sc) + (root * sq) * (root * sc)


def quantize_rows(rows: np.ndarray):
    """Symmetric per-row int8 quantization of f32 embedding rows.

    Returns ``(codes int8 (N, D), scale f32 (N,))`` with
    ``codes * scale[:, None]`` the reconstruction.  All-zero rows (empty
    records) keep scale 0 — they reconstruct to zero and cosine 0, the
    same behavior the f32 path has for them.
    """
    rows = np.asarray(rows, dtype=np.float32)
    peak = np.abs(rows).max(axis=1)
    scale = (peak / 127.0).astype(np.float32)
    inv = np.where(scale > 0.0, 1.0 / np.where(scale > 0.0, scale, 1.0), 0.0)
    codes = np.rint(rows * inv[:, None]).astype(np.int8)
    return codes, scale


def corpus_tensors_from_f32(rows: np.ndarray, storage: str):
    """f32 embedding rows -> the ANN_PROP tensor dict for ``storage``
    ("int8" or a float dtype name).  ONE conversion point shared by the
    serial extractor, the shared-memory parallel extractor's parent-side
    assembly, and the encoder itself."""
    if storage == "int8":
        codes, scale = quantize_rows(rows)
        return {ANN_TENSOR: codes, ANN_SCALE: scale}
    return {ANN_TENSOR: rows.astype(STORAGE_DTYPE)}


def dequantize_rows(tree) -> np.ndarray:
    """ANN_PROP tensor dict -> f32 rows (host side: k-means training,
    explain provenance).  Accepts both storage layouts."""
    emb = tree[ANN_TENSOR]
    if emb.dtype == np.int8:
        return emb.astype(np.float32) * np.asarray(
            tree[ANN_SCALE], dtype=np.float32
        )[:, None]
    return np.asarray(emb, dtype=np.float32)


_NGRAM = 3

# Vectorized n-gram hashing: one odd multiplier per codepoint position in
# the window plus a murmur3-style finalizer, all in wrapping uint64 numpy
# arithmetic — the whole record hashes in a handful of array ops instead of
# a per-byte Python loop (ingest-side hot path for large corpora).
_H_MULT = (
    np.uint64(0x9E3779B97F4A7C15),
    np.uint64(0xC2B2AE3D27D4EB4F),
    np.uint64(0x165667B19E3779F9),
    np.uint64(0x27D4EB2F165667C5),
    np.uint64(0x85EBCA77C2B2AE63),
)
assert _NGRAM <= len(_H_MULT), "add a multiplier per n-gram position"
_FM1 = np.uint64(0xFF51AFD7ED558CCD)
_FM2 = np.uint64(0xC4CEB9FE1A85EC53)

_SALTS: Dict[str, np.uint64] = {}


def _native_embed():
    """The C++ bulk encoder, or None (pure numpy fallback/oracle)."""
    if _NGRAM != 3:  # the native kernel hardcodes the trigram window
        return None
    try:
        from .. import native
    except Exception:  # pragma: no cover - import is cheap and total
        return None
    return native if native.available() else None


def _salt(prop: str) -> np.uint64:
    # separate salt per property so "oslo" in NAME and "oslo" in CAPITAL
    # hash to different buckets — field-tagged n-grams, like Lucene's
    # per-field terms
    s = _SALTS.get(prop)
    if s is None:
        s = _SALTS[prop] = np.uint64(F.fnv1a64(prop))
    return s


def _hash_ngrams(value: str, salt: np.uint64) -> np.ndarray:
    """uint64 hashes of all character n-grams of `` value `` (padded)."""
    padded = f" {value.lower()} "
    cp = np.frombuffer(
        padded.encode("utf-32-le", "surrogatepass"), dtype=np.uint32
    ).astype(np.uint64)
    if cp.size < _NGRAM:
        cp = np.pad(cp, (0, _NGRAM - cp.size))
    with np.errstate(over="ignore"):
        nwin = cp.size - _NGRAM + 1
        h = salt
        for j in range(_NGRAM):
            h = h ^ (cp[j:j + nwin] * _H_MULT[j])
        h ^= h >> np.uint64(33)
        h *= _FM1
        h ^= h >> np.uint64(29)
        h *= _FM2
        h ^= h >> np.uint64(32)
    return h


def embed_values(prop_values: Sequence[tuple], dim: int) -> np.ndarray:
    """One L2-normalized signed-hash embedding from (property, value) pairs."""
    vec = np.zeros((dim,), dtype=np.float32)
    hashes = [
        _hash_ngrams(value, _salt(prop)) for prop, value in prop_values
    ]
    if not hashes:
        return vec
    uniq, counts = np.unique(np.concatenate(hashes), return_counts=True)
    buckets = (uniq % np.uint64(dim)).astype(np.int64)
    signs = np.where(
        (uniq >> np.uint64(32)) & np.uint64(1), 1.0, -1.0
    ).astype(np.float32)
    # sublinear tf weighting
    np.add.at(vec, buckets, signs * np.sqrt(counts).astype(np.float32))
    norm = float(np.linalg.norm(vec))
    if norm > 0.0:
        vec /= norm
    return vec


class RecordEncoder:
    """Schema-bound encoder: Record -> (dim,) normalized f32 embedding."""

    def __init__(self, schema, dim: int):
        self.dim = dim
        # every comparison property contributes to blocking; recall against
        # brute force is measured, not assumed (SURVEY.md section 7 hard
        # part 5), and more fields can only add evidence
        self.props: List[str] = [p.name for p in schema.comparison_properties()]
        # corpus storage mode, resolved ONCE at construction: an index
        # whose env flips mid-life must never mix dtypes in one corpus
        # (the snapshot fingerprint and feature-cache key both carry this)
        self.storage = storage_name()

    def encode(self, record: Record) -> np.ndarray:
        pairs = []
        for name in self.props:
            for value in record.get_values(name):
                if value:
                    pairs.append((name, value))
        return embed_values(pairs, self.dim)

    def encode_corpus(self, records: Sequence[Record]) -> np.ndarray:
        """Corpus-resident embeddings: ``encode_batch`` in STORAGE_DTYPE.

        bf16-mode helper kept for the benches/dryrun that assemble the
        corpus tree by hand; storage-mode-aware callers (ops.features)
        use ``corpus_tensors`` instead."""
        return self.encode_batch(records).astype(STORAGE_DTYPE)

    def corpus_tensors(self, records: Sequence[Record]) -> Dict[str, np.ndarray]:
        """The ANN_PROP tensor dict for a record batch under this
        encoder's storage mode ({emb} in bf16, {emb, scale} in int8)."""
        return corpus_tensors_from_f32(self.encode_batch(records),
                                       self.storage)

    def encode_batch(self, records: Sequence[Record]) -> np.ndarray:
        if not records:
            return np.zeros((0, self.dim), dtype=np.float32)
        native = _native_embed()
        if native is not None:
            return self._encode_batch_native(records, native)
        return np.stack([self.encode(r) for r in records])

    def _encode_batch_native(self, records: Sequence[Record],
                             native) -> np.ndarray:
        # bulk path through the C++ library: one FFI call for the whole
        # chunk (tests pin it to the numpy path's exact output)
        strings: List[str] = []
        salts: List[np.uint64] = []
        rec_off = np.zeros(len(records) + 1, dtype=np.int64)
        empty: List[str] = []
        prop_salts = [(name, _salt(name)) for name in self.props]
        for i, record in enumerate(records):
            values_map = record._values  # read-only peek (no copies)
            for name, salt in prop_salts:
                for value in values_map.get(name, empty):
                    if value:  # defensive: keep parity with encode()'s guard
                        strings.append(f" {value.lower()} ")
                        salts.append(salt)
            rec_off[i + 1] = len(strings)
        return native.embed_batch(
            strings, np.asarray(salts, dtype=np.uint64), rec_off,
            self.dim,
        )


def _fused_retrieval(q_emb, corpus_emb, corpus_valid, corpus_deleted,
                     corpus_group, query_group, query_row, *,
                     top_c: int, group_filtering: bool, row_offset,
                     recall_target: float):
    """The Pallas fast path of ``retrieval_scan``: fused matmul + mask +
    segment-max in VMEM (ops.pallas_kernels.retrieval_segmax), then an
    approximate top-C over the SEG-x-smaller segment winners.  Returns
    (top_sim, top_idx) or None when the shapes don't fit the kernel
    (caller falls back to the XLA scan)."""
    import jax.numpy as jnp
    from jax import lax

    from . import pallas_kernels as pk

    n, d = corpus_emb.shape
    q = q_emb.shape[0]
    seg = env_int("DEVICE_ANN_SEG", 64)
    if d % 128 != 0 or seg <= 0 or seg & (seg - 1) or n < 2 * seg:
        return None
    # corpus tile: sized so the (TC, QP) f32 score tile stays ~<=8 MB of
    # VMEM; a power of two >= 1024 (the mask operand's (TC, 128) int8
    # block needs TC/128 >= 8 sublanes) that divides the capacity
    qp = -(-q // 128) * 128
    tc = n & -n  # largest power-of-2 divisor of the capacity
    tc = min(tc, 2048, (1 << 21) // qp)  # tc*qp*4B <= 8 MB of VMEM
    nbins = n // seg
    # Bin-count floor: expected segment-phase recall of the true top-C is
    # ~1 - C/(2*nbins) (birthday collisions into nbins strided bins), so
    # honoring recall_target needs nbins >= C / (1 - target) — with slack
    # for the approx-over-bins second stage, which carries its own
    # recall_target reduction.  Below the floor (small corpora, or an
    # escalated C approaching the bin count = a saturated candidate
    # budget) drop to the per-chunk approx scan, whose reduction adapts
    # to its input size.  Empirically this floor is what separates the
    # 10M run's 0.975 measured recall from the 10k-corpus case that
    # silently lost 0.989-confidence pairs at 256 bins (r5 bringup).
    min_bins = int(top_c / max(1e-3, 1.0 - recall_target))
    if tc < max(1024, seg * 8) or n % tc or nbins < min_bins:
        return None

    if qp != q:
        pad = qp - q
        q_emb = jnp.pad(q_emb, ((0, pad), (0, 0)))
        # padded queries: no self-row; their outputs are sliced away
        # below, so their group value only needs to be well-formed (it
        # clips to -1, the dedup no-group encoding)
        query_row = jnp.pad(query_row, (0, pad), constant_values=-1)
        query_group = jnp.pad(query_group, (0, pad),
                              constant_values=-1)

    qT = q_emb.astype(jnp.bfloat16).T
    # Encoded int8 mask broadcast across a 128-lane axis — tile-native,
    # where an (N, 1) int32 column operand would T(8,128)-pad 128x into
    # a multi-GB temp at the flagship scale (see pk.GROUP_OFFSET note).
    # POLICY: this encodes exactly scoring.candidate_mask (the one-place
    # eligibility policy — keep the two in sync): live & not tombstoned,
    # group exclusion, self-row exclusion.  int8 range is safe because
    # group ids are the <group> element ordinals 1..2 (core/config.py
    # enforces exactly two groups) or -1; both sides clip identically so
    # the compare could only coarsen together, never diverge.
    live = corpus_valid & ~corpus_deleted
    enc_col = jnp.where(
        live,
        (jnp.clip(corpus_group, -1, 100)
         + jnp.int32(pk.GROUP_OFFSET)).astype(jnp.int8),
        jnp.int8(0),
    )
    enc = jnp.broadcast_to(enc_col[:, None], (n, 128))
    # the kernel masks in LOCAL row coordinates; shift the query's own
    # global row down (negative stays impossible-to-match)
    qrow_local = (query_row - row_offset)[None, :].astype(jnp.int32)
    qgroup_enc = (jnp.clip(query_group, -1, 100)
                  + pk.GROUP_OFFSET)[None, :].astype(jnp.int32)

    seg_max, seg_arg = pk.retrieval_segmax(
        qT, corpus_emb.astype(jnp.bfloat16), enc, qrow_local,
        qgroup_enc, tc=tc, seg=seg, group_filtering=group_filtering,
    )
    smax = seg_max.T[:q]                                  # (Q, nbins)
    sarg = seg_arg.T[:q]
    top_sim, bin_sel = lax.approx_max_k(
        smax, top_c, recall_target=recall_target
    )
    local = jnp.take_along_axis(sarg, bin_sel, axis=1)
    top_idx = jnp.where(
        top_sim < jnp.float32(-1e30), jnp.int32(-1), local + row_offset
    )
    return top_sim, top_idx


def as_emb_tree(x) -> Dict:
    """Normalize an embedding operand to the ANN_PROP tensor-dict layout.

    Bare arrays (the legacy bf16 call convention used by the benches and
    the fused-retrieval tests) wrap as ``{ANN_TENSOR: x}``; dicts — the
    corpus tree's ANN_PROP entry, carrying the int8 scale when
    DUKE_EMB_INT8 storage is active — pass through."""
    return x if isinstance(x, dict) else {ANN_TENSOR: x}


def is_int8_tree(tree: Dict) -> bool:
    return ANN_SCALE in tree


def chunk_sims(q_tree: Dict, c_emb, c_scale=None):
    """(Q, chunk) cosine-score tile for one corpus chunk.

    bf16 storage: both operands cast to bf16, f32 MXU accumulation — the
    pre-existing path, bit-for-bit.  int8 storage: int8 x int8 -> int32
    MXU matmul (exact: D * 127^2 << 2^31) rescaled by the per-row
    query/corpus scales; roughly double the matmul throughput of bf16 at
    half the HBM traffic."""
    import jax
    import jax.numpy as jnp

    q_emb = q_tree[ANN_TENSOR]
    if c_scale is not None:
        raw = jax.lax.dot_general(
            q_emb, c_emb,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
        return (raw * q_tree[ANN_SCALE][:, None]) * c_scale[None, :]
    return jax.lax.dot_general(
        q_emb.astype(jnp.bfloat16), c_emb.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def retrieval_scan(q_emb, corpus_emb, corpus_valid, corpus_deleted,
                   corpus_group, query_group, query_row, *,
                   chunk: int, top_c: int, group_filtering: bool,
                   row_offset=0):
    """Blockwise cosine top-C over the corpus embedding matrix.

    ``q_emb`` / ``corpus_emb`` accept either a bare matrix (bf16 legacy
    convention) or the ANN_PROP tensor dict — ``{emb}`` for float
    storage, ``{emb, scale}`` for DUKE_EMB_INT8 (see ``chunk_sims``).

    Same scan/mask/merge skeleton as ``ops.scoring.scan_topk`` but the chunk
    score is a single (Q, D) x (D, chunk) matmul in bf16 with f32
    accumulation — the MXU path.  Returns (top_sim, top_index) with global
    row indices (``row_offset`` as in scan_topk for sharded use).

    The scan chunk is widened to ``DEVICE_ANN_RETRIEVAL_CHUNK`` (default
    65536; see BASELINE.md r5 retrieval table) when the corpus allows:
    the matmul is so cheap per row that per-step overhead (top-C merge,
    scan bookkeeping) dominates with small chunks.  Capacities are
    power-of-2 multiples of the base chunk, so any power-of-2 widening
    divides evenly.

    Per-chunk top-C uses ``lax.approx_max_k`` — the TPU-native
    PartialReduce op (Chern et al. 2022): instead of fully sorting the
    (Q, chunk) similarity tile each step (a vector-unit sort that left
    the r4 scan ~0.4% MFU, two orders of magnitude off the matmul+HBM
    roofline), the chunk is reduced bin-wise to ~C survivors at a
    configurable expected recall, and only the (Q, 2C) merge with the
    running carry is sorted exactly.  Recall loss only ever shrinks the
    candidate *set* (never corrupts a score — candidates are rescored
    exactly either way), the escalation loop still widens C on
    saturation, and ``DEVICE_ANN_RECALL_TARGET`` / ``DEVICE_ANN_EXACT_TOPK=1``
    restore tighter or exact semantics.  This is the TPU answer to the
    reference's "single biggest influence on search performance" knob —
    its Lucene candidate-search limits (IncrementalLuceneDatabase.java:
    349-358 ``max_search_hits``): both trade bounded blocking recall for
    retrieval speed, and both rescore survivors exactly.
    """
    wide = env_int("DEVICE_ANN_RETRIEVAL_CHUNK", 65536)
    cap_total = corpus_valid.shape[0]
    while chunk < wide and chunk * 2 <= cap_total and cap_total % (chunk * 2) == 0:
        chunk *= 2
    import jax.numpy as jnp
    from jax import lax

    from . import scoring

    q_tree = as_emb_tree(q_emb)
    c_tree = as_emb_tree(corpus_emb)
    int8 = is_int8_tree(c_tree)
    q_emb = q_tree[ANN_TENSOR]
    corpus_emb = c_tree[ANN_TENSOR]
    q = q_emb.shape[0]
    cap = corpus_valid.shape[0]
    nchunks = cap // chunk

    neg = jnp.float32(scoring.NEG_INF)
    init_sim = jnp.full((q, top_c), neg, jnp.float32)
    init_idx = jnp.full((q, top_c), -1, jnp.int32)

    # exact full-sort merge when forced, or when the chunk is so narrow
    # (escalated C approaching chunk width) that the bin reduction cannot
    # shrink anything worth the second merge step
    exact = env_flag("DEVICE_ANN_EXACT_TOPK", False) or top_c * 4 >= chunk
    recall_target = env_float("DEVICE_ANN_RECALL_TARGET", 0.99)

    from . import pallas_kernels as pk

    if (
        not exact
        and not int8  # the fused segmax kernel stages bf16 operands only
        and env_flag("DEVICE_ANN_FUSED", True)
        and pk.pallas_enabled()
    ):
        fused = _fused_retrieval(
            q_emb, corpus_emb, corpus_valid, corpus_deleted, corpus_group,
            query_group, query_row, top_c=top_c,
            group_filtering=group_filtering, row_offset=row_offset,
            recall_target=recall_target,
        )
        if fused is not None:
            return fused

    def body(carry, ci):
        top_sim, top_idx = carry
        start = ci * chunk
        emb_c = lax.dynamic_slice_in_dim(corpus_emb, start, chunk, axis=0)
        scale_c = (
            lax.dynamic_slice_in_dim(c_tree[ANN_SCALE], start, chunk)
            if int8 else None
        )
        sims = chunk_sims(q_tree, emb_c, scale_c)  # (Q, chunk)

        cvalid = lax.dynamic_slice_in_dim(corpus_valid, start, chunk)
        cdel = lax.dynamic_slice_in_dim(corpus_deleted, start, chunk)
        cgroup = lax.dynamic_slice_in_dim(corpus_group, start, chunk)
        cidx = row_offset + start + jnp.arange(chunk, dtype=jnp.int32)

        mask = scoring.candidate_mask(
            cvalid, cdel, cgroup, cidx, query_group, query_row,
            group_filtering,
        )
        sims = jnp.where(mask, sims, neg)

        if exact:
            merged_sim = jnp.concatenate([top_sim, sims], axis=1)
            merged_idx = jnp.concatenate(
                [top_idx, jnp.broadcast_to(cidx[None, :], (q, chunk))],
                axis=1,
            )
        else:
            chunk_sim, chunk_arg = lax.approx_max_k(
                sims, top_c, recall_target=recall_target
            )
            merged_sim = jnp.concatenate([top_sim, chunk_sim], axis=1)
            # carry entries come FIRST in the concat: lax.top_k breaks
            # ties by position, so all-masked (NEG_INF) chunk survivors
            # can never displace the carry's -1 "empty slot" sentinels —
            # the invariant build_ann_scorer's `retrieved` mask rests on
            merged_idx = jnp.concatenate(
                [top_idx,
                 row_offset + start + chunk_arg.astype(jnp.int32)],
                axis=1,
            )
        top_sim, sel = lax.top_k(merged_sim, top_c)
        top_idx = jnp.take_along_axis(merged_idx, sel, axis=1)
        return (top_sim, top_idx), None

    (top_sim, top_idx), _ = lax.scan(
        body, (init_sim, init_idx), jnp.arange(nchunks, dtype=jnp.int32)
    )
    return top_sim, top_idx
