"""Shared device memory arena: multi-tenant HBM residency (ISSUE 19
tentpole a).

One process serving hundreds of workloads cannot let every tenant pin
its padded corpus mirrors in HBM forever — idle tenants' padding would
crowd out hot ones and the first tenant past the budget dies on an
opaque XLA OOM.  The arena is the process-wide residency ledger that
fixes both:

  * every device-corpus upload **admits** through :meth:`DeviceArena.
    admit` first.  Admission holds the per-tenant device mirror bytes
    against the HBM budget (``telemetry.memory.budget_bytes`` — the
    ``DUKE_HBM_BUDGET_MB`` ceiling, else the backend's reported limit,
    else 16 GiB);
  * past the budget, the coldest resident tenants **spill**: their
    device mirrors drop (the numpy host mirror is the durable tier —
    effectively a host-pinned copy that re-uploads on demand) and the
    next query **faults the corpus back in** through the normal
    dirty-full upload path.  Victim order is the cost ledger's
    accumulated per-tenant device-seconds with admission recency as the
    tiebreak — an idle tenant evicts before a busy one;
  * when eviction cannot make room (the admitting tenant alone exceeds
    the budget, or every other resident is spill-exempt), admission
    raises :class:`ArenaAdmissionError` — the HTTP layer maps it to a
    loud 503 instead of letting the device allocator OOM.

``DUKE_ARENA=0`` disables the subsystem: ``admit`` becomes a no-op and
per-workload tensors stay pinned exactly as before (the legacy CI leg).

Lock order: ``DeviceArena._lock`` is OUTER to every corpus
``_upload_lock`` — admission runs *before* the caller takes its own
upload lock (engine.device_matcher.DeviceCorpus.device_arrays), and a
spill inside admission takes only the *victim's* upload lock.  A victim
mid-upload (past its own admit, inside its upload lock) just finishes;
the spill lands right after, and the victim's next query re-admits (one
transient fault).  The arena never spills the admitting owner.

Scrape surfaces (registered on ``telemetry.GLOBAL`` at import, like the
ledger collectors): ``duke_arena_bytes{tier}`` (device = resident lease
bytes, host = spilled lease bytes living on their host mirrors) and
``duke_arena_faults_total`` (spill→re-upload round trips).  The HBM
ledger attributes resident arena bytes ONCE (owner = arena); tenants
keep per-workload *logical* views (telemetry.memory ``logical``
registrations) so attribution survives without double counting.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional

from ..telemetry import GLOBAL
from ..telemetry.env import env_flag
from ..telemetry.registry import FamilySnapshot

logger = logging.getLogger("arena")

__all__ = [
    "ARENA",
    "ArenaAdmissionError",
    "DeviceArena",
    "arena_enabled",
]


def arena_enabled() -> bool:
    """``DUKE_ARENA=0`` pins per-workload tensors exactly as before."""
    return env_flag("DUKE_ARENA", True)


class ArenaAdmissionError(Exception):
    """Admission refused: the corpus does not fit the HBM budget even
    after spilling every eligible resident tenant.  The HTTP layer maps
    this to 503 — the loud, actionable alternative to an allocator OOM
    (raise ``DUKE_HBM_BUDGET_MB``, shrink the corpus, or shed the
    tenant)."""

    def __init__(self, label: str, need: int, budget: int, resident: int):
        super().__init__(
            f"HBM budget exhausted admitting {label or 'corpus'}: "
            f"need {need} bytes, budget {budget}, "
            f"{resident} still resident after spilling"
        )
        self.need = need
        self.budget = budget
        self.resident = resident


def _weak_callable(fn):
    """Resolver for an owner-supplied callback that must not pin the
    owner: bound methods (corpus.spill_device) are held through
    ``WeakMethod`` so the lease's weakref pruning still fires; plain
    functions/lambdas are held directly (they close over weakrefs by
    convention — see engine.workload._arena_heat)."""
    if fn is None:
        return lambda: None
    if hasattr(fn, "__self__"):
        wm = weakref.WeakMethod(fn)
        return wm
    return lambda: fn


class _Lease:
    """One corpus' residency record (guarded by: DeviceArena._lock,
    except ``heat_fn`` which is immutable after creation)."""

    __slots__ = ("ref", "label", "nbytes", "resident", "spilled_once",
                 "last_touch", "spill_fn", "heat_fn", "faults")

    def __init__(self, owner, label: str, spill_fn, heat_fn):
        self.ref = weakref.ref(owner)
        self.label = label
        self.nbytes = 0
        self.resident = False
        self.spilled_once = False  # distinguishes fault-ins from cold starts
        self.last_touch = 0.0
        self.spill_fn = _weak_callable(spill_fn)
        self.heat_fn = _weak_callable(heat_fn)
        self.faults = 0

    def heat(self) -> float:
        fn = self.heat_fn()
        if fn is None:
            return 0.0
        try:
            return float(fn())
        except Exception:
            return 0.0


class DeviceArena:
    """Process-wide residency ledger for device corpus mirrors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._leases: Dict[int, _Lease] = {}  # id(owner) -> lease; guarded by: self._lock
        self.faults = 0       # spill -> fault-in round trips; guarded by: self._lock [writes]
        self.spills = 0       # guarded by: self._lock [writes]
        self.admissions = 0   # guarded by: self._lock [writes]
        self.rejections = 0   # guarded by: self._lock [writes]

    # -- admission ----------------------------------------------------------

    def admit(self, owner, nbytes: int, *, spill: Callable[[], int],
              label: str = "", heat: Optional[Callable[[], float]] = None
              ) -> None:
        """Hold ``nbytes`` of device residency for ``owner``, spilling
        colder tenants as needed.  Call BEFORE taking the owner's upload
        lock (lock order: arena outer).  Idempotent and cheap while the
        owner is already resident at the same size (one lock + dict hit).
        Raises :class:`ArenaAdmissionError` when the budget cannot fit
        the owner even after spilling everything eligible."""
        if not arena_enabled():
            return
        if self is ARENA:
            # ledger resets (tests) drop the import-time enrollment;
            # re-register lazily so resident slabs stay attributed.
            # Unlocked membership probe: register() is idempotent.
            from ..telemetry import memory

            if id(ARENA) not in memory._ENTRIES:
                _enroll_ledger()
        nbytes = int(nbytes)
        victims: List[_Lease] = []
        with self._lock:
            lease = self._leases.get(id(owner))
            if lease is None:
                lease = self._leases[id(owner)] = _Lease(
                    owner, label, spill, heat)
            lease.last_touch = time.monotonic()
            if label:
                lease.label = label
            if heat is not None:
                lease.heat_fn = _weak_callable(heat)
            if lease.resident and lease.nbytes == nbytes:
                return  # steady state: already resident at this size
            budget = self._budget_bytes()
            resident = sum(
                entry.nbytes for entry in self._live_leases()
                if entry.resident and entry is not lease)
            if resident + nbytes > budget:
                victims = self._pick_victims(
                    lease, resident + nbytes - budget)
                resident -= sum(v.nbytes for v in victims)
            if resident + nbytes > budget:
                self.rejections += 1
                raise ArenaAdmissionError(
                    lease.label, nbytes, int(budget), int(resident))
            if lease.spilled_once and not lease.resident:
                lease.faults += 1
                self.faults += 1
            was_resident = lease.resident
            lease.resident = True
            lease.nbytes = nbytes
            if not was_resident:
                self.admissions += 1
            # spill victims while still holding the arena lock: each
            # spill takes only the VICTIM's upload lock (never the
            # admitting owner's — _pick_victims excludes it), so the
            # arena-outer/upload-inner order holds on every path
            for victim in victims:
                self._spill_locked(victim)

    def _budget_bytes(self) -> float:
        from ..telemetry import memory

        return memory.budget_bytes()[0]

    def _live_leases(self) -> List[_Lease]:
        """Leases whose owners are alive, pruning the rest (call with
        self._lock held)."""
        dead = [key for key, entry in self._leases.items()
                if entry.ref() is None]
        for key in dead:
            del self._leases[key]
        return list(self._leases.values())

    def _pick_victims(self, admitting: _Lease, shortfall: int
                      ) -> List[_Lease]:
        """Coldest-first resident leases covering ``shortfall`` bytes
        (call with self._lock held).  Order: accumulated cost-ledger
        device-seconds ascending (idle tenants first), admission recency
        as tiebreak — the ISSUE's 'LRU by per-workload device-seconds'."""
        candidates = [
            entry for entry in self._live_leases()
            if entry.resident and entry is not admitting and entry.nbytes > 0
        ]
        candidates.sort(key=lambda e: (e.heat(), e.last_touch))
        out: List[_Lease] = []
        freed = 0
        for entry in candidates:
            if freed >= shortfall:
                break
            out.append(entry)
            freed += entry.nbytes
        return out

    def _spill_locked(self, lease: _Lease) -> None:  # dukecheck: holds self._lock
        """Drop one victim's device mirrors (call with self._lock held;
        takes the victim's upload lock inside — see module lock order)."""
        try:
            fn = lease.spill_fn()
            if fn is not None:
                fn()
        except Exception:  # a wedged victim must not fail the admission
            logger.exception("arena spill failed for %s", lease.label)
        lease.resident = False
        lease.spilled_once = True
        self.spills += 1
        logger.info("arena spilled %s (%d bytes) to host tier",
                    lease.label or "corpus", lease.nbytes)

    # -- bookkeeping --------------------------------------------------------

    def note_released(self, owner) -> None:
        """Owner dropped its device mirrors outside the arena (close,
        snapshot restore churn): keep the books honest."""
        with self._lock:
            lease = self._leases.get(id(owner))
            if lease is not None:
                lease.resident = False

    def forget(self, owner) -> None:
        with self._lock:
            self._leases.pop(id(owner), None)

    def tier_bytes(self) -> Dict[str, int]:
        """{tier: bytes}: device = resident leases, host = spilled
        leases (their host mirrors are the fault-in source)."""
        device = 0
        host = 0
        with self._lock:
            for entry in self._live_leases():
                if entry.resident:
                    device += entry.nbytes
                elif entry.spilled_once:
                    host += entry.nbytes
        return {"device": device, "host": host}

    def device_bytes(self) -> int:
        return self.tier_bytes()["device"]

    def debug_snapshot(self) -> Dict[str, object]:
        """The /debug/memory ``arena`` block."""
        with self._lock:
            rows = [
                {"label": entry.label,
                 "bytes": int(entry.nbytes),
                 "resident": bool(entry.resident),
                 "faults": int(entry.faults),
                 "heat_device_seconds": round(entry.heat(), 6)}
                for entry in self._live_leases()
            ]
            counters = {
                "admissions": self.admissions,
                "spills": self.spills,
                "faults": self.faults,
                "rejections": self.rejections,
            }
        tiers = self.tier_bytes()
        return {
            "enabled": arena_enabled(),
            "tiers": tiers,
            "leases": rows,
            **counters,
        }

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._leases.clear()
            self.faults = 0
            self.spills = 0
            self.admissions = 0
            self.rejections = 0


ARENA = DeviceArena()


def _arena_components() -> Dict[str, int]:
    """The arena's HBM-ledger registration: resident slab bytes,
    attributed ONCE here (owner = arena) while tenants carry logical
    views — telemetry.memory excludes those views from the budget
    totals, so shared slabs never double-count against headroom."""
    nbytes = ARENA.device_bytes()
    return {"corpus_tensors": nbytes} if nbytes else {}


def _enroll_ledger() -> None:
    from ..telemetry import memory

    memory.register(ARENA, "arena", "", _arena_components)


_enroll_ledger()


def collect() -> List[FamilySnapshot]:
    """Scrape-time collector (registered on ``telemetry.GLOBAL``)."""
    tiers = ARENA.tier_bytes()
    return [
        FamilySnapshot(
            "duke_arena_bytes", "gauge",
            "Shared device-memory arena bytes by tier (device = "
            "resident corpus mirrors, host = spilled tenants waiting "
            "to fault back in)",
            [("", (("tier", tier),), float(nbytes))
             for tier, nbytes in sorted(tiers.items())]),
        FamilySnapshot(
            "duke_arena_faults_total", "counter",
            "Corpus fault-ins: a spilled tenant's first query "
            "re-admitted and re-uploaded its device mirrors",
            [("", (), float(ARENA.faults))]),
    ]


GLOBAL.register_collector(collect)
