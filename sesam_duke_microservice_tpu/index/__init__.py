from .base import CandidateIndex
from .inverted import InvertedIndex

__all__ = ["CandidateIndex", "InvertedIndex"]
