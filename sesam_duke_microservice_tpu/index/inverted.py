"""Host token inverted index — the Lucene-parity blocking backend.

Reproduces the candidate-retrieval semantics of the reference's
``IncrementalLuceneDatabase`` (IncrementalLuceneDatabase.java:57-592):

  * StandardAnalyzer-style analysis (lowercase word tokens, English stop
    words) for regular properties; identity-ish fields (ID, dukeDatasetId,
    dukeGroupNo, dukeOriginalEntityId) indexed as exact terms
    (NOT_ANALYZED, lines 543-548);
  * candidate query = OR over analyzed tokens of all lookup-property values
    (MUST when the property's lookup is REQUIRED), MUST_NOT on the query
    record's dukeGroupNo (record linkage) and on dukeDeleted=true
    (lines 459-492);
  * classic Lucene practical scoring (tf·idf²·fieldNorm·coord·queryNorm)
    with the ``min_relevance`` cut and ``max_search_hits`` cap;
  * the ``EstimateResultTracker`` adaptive result-limit estimation: limit
    starts at 100, retries ×5 while the result set fills the limit, ring
    buffer of the last 10 non-empty result sizes re-estimates the limit
    (lines 349-423; the copied comment calls this "the single biggest
    influence on search performance").  The reference's division-by-zero
    (NaN) when the first ring entry is zero (SURVEY.md quirk Q3) is fixed
    here by guarding the empty-window case;
  * Lucene-style visibility: records become searchable only at ``commit()``;
    re-indexing a record first deletes the previous copy by ID
    (lines 516-517).

Uncommitted (pending) operations and committed state are kept separate so
``Processor.deduplicate`` ordering — index all, commit, then query — behaves
exactly as with a real IndexWriter/IndexSearcher pair.
"""

from __future__ import annotations

import heapq
import math
import re
from collections import Counter, defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ..core.config import DukeSchema, MatchTunables
from ..core.records import (
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    Lookup,
    Record,
)
from .base import CandidateIndex

# Lucene StandardAnalyzer's default English stop set
_STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)

# fields indexed as single exact terms (IncrementalLuceneDatabase.java:543-548)
_NOT_ANALYZED = frozenset(
    {ID_PROPERTY_NAME, "dukeDatasetId", GROUP_NO_PROPERTY_NAME, "dukeOriginalEntityId"}
)

SEARCH_EXPANSION_FACTOR = 1  # IncrementalLuceneDatabase.java:70

# Lucene FuzzyQuery rewrites to at most 50 terms; same cap here.
_MAX_FUZZY_EXPANSIONS = 50


def _osa_distance(a: str, b: str, limit: int) -> int:
    """Optimal-string-alignment edit distance, early-exiting past ``limit``.

    Counts adjacent transpositions as one edit — the distance Lucene's
    FuzzyQuery automaton uses (transpositions=true), which plain
    Levenshtein would overcount ('ab' -> 'ba' is 1, not 2).
    """
    la, lb = len(a), len(b)
    if abs(la - lb) > limit:
        return limit + 1
    prev2: List[int] = []
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        ca = a[i - 1]
        for j in range(1, lb + 1):
            cost = 0 if ca == b[j - 1] else 1
            d = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            if (i > 1 and j > 1 and ca == b[j - 2]
                    and a[i - 2] == b[j - 1]):
                d = min(d, prev2[j - 2] + 1)
            cur[j] = d
        if min(cur) > limit:
            return limit + 1
        prev2, prev = prev, cur
    return prev[lb]


def analyze(value: str) -> List[str]:
    return [
        t for t in (m.group(0).lower() for m in _TOKEN_RE.finditer(value))
        if t not in _STOP_WORDS
    ]


class _Doc:
    __slots__ = ("slot", "record", "field_tokens", "field_lengths")

    def __init__(self, slot: int, record: Record):
        self.slot = slot
        self.record = record
        self.field_tokens: Dict[str, Counter] = {}
        self.field_lengths: Dict[str, int] = {}
        for prop in record.properties():
            tokens: List[str] = []
            for v in record.get_values(prop):
                if prop in _NOT_ANALYZED:
                    tokens.append(v)
                else:
                    tokens.extend(analyze(v))
            if tokens:
                self.field_tokens[prop] = Counter(tokens)
                self.field_lengths[prop] = len(tokens)


class _ResultEstimator:
    """EstimateResultTracker parity (IncrementalLuceneDatabase.java:359-423)."""

    def __init__(self):
        self.limit = 100
        self.prevsizes = [0] * 10
        self.sizeix = 0

    def record_result(self, size: int) -> None:
        self.prevsizes[self.sizeix] = size
        self.sizeix += 1
        if self.sizeix == len(self.prevsizes):
            self.sizeix = 0
            self.limit = max(int(self._average() * SEARCH_EXPANSION_FACTOR), self.limit)

    def _average(self) -> float:
        total = 0
        ix = 0
        while ix < len(self.prevsizes) and self.prevsizes[ix] != 0:
            total += self.prevsizes[ix]
            ix += 1
        if ix == 0:
            return 0.0  # reference would divide by zero here (quirk Q3)
        return total / ix


class InvertedIndex(CandidateIndex):
    def __init__(self, schema: DukeSchema, tunables: Optional[MatchTunables] = None):
        self.schema = schema
        self.tunables = tunables or MatchTunables()
        self._estimator = _ResultEstimator()
        self._indexing_disabled = False

        self._next_slot = 0
        self._docs: Dict[int, _Doc] = {}                # committed, by slot
        self._live = 0                                  # non-dukeDeleted docs
        self._id_to_slot: Dict[str, int] = {}
        self._postings: Dict[Tuple[str, str], Set[int]] = defaultdict(set)
        # field -> term-length -> terms; mirrors _postings' key set (kept in
        # sync at the two write sites below) so fuzzy expansion only scans
        # the +/-2-length buckets
        self._vocab: Dict[str, Dict[int, Set[str]]] = defaultdict(dict)
        self._fuzzy_cache: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        self._pending: List[Tuple[str, object]] = []    # ("add", Record) | ("del", id)

    # -- write path ---------------------------------------------------------

    def index(self, record: Record) -> None:
        if self._indexing_disabled:
            return
        rid = record.record_id
        if rid is not None:
            self._pending.append(("del", rid))
        self._pending.append(("add", record))

    def delete(self, record: Record) -> None:
        rid = record.record_id
        if rid is not None:
            self._pending.append(("del", rid))

    def set_indexing_disabled(self, disabled: bool) -> None:
        self._indexing_disabled = disabled

    def commit(self) -> None:
        if self._pending:
            self._fuzzy_cache.clear()
        for op, payload in self._pending:
            if op == "del":
                self._remove_committed(payload)
            else:
                self._add_committed(payload)
        self._pending.clear()

    def _add_committed(self, record: Record) -> None:
        slot = self._next_slot
        self._next_slot += 1
        doc = _Doc(slot, record)
        self._docs[slot] = doc
        if not record.is_deleted():
            self._live += 1
        rid = record.record_id
        if rid is not None:
            self._id_to_slot[rid] = slot
        for field, counts in doc.field_tokens.items():
            for token in counts:
                self._postings[(field, token)].add(slot)
                self._vocab[field].setdefault(len(token), set()).add(token)

    def _remove_committed(self, record_id: str) -> None:
        slot = self._id_to_slot.pop(record_id, None)
        if slot is None:
            return
        doc = self._docs.pop(slot)
        if not doc.record.is_deleted():
            self._live -= 1
        for field, counts in doc.field_tokens.items():
            for token in counts:
                bucket = self._postings.get((field, token))
                if bucket is not None:
                    bucket.discard(slot)
                    if not bucket:
                        del self._postings[(field, token)]
                        by_len = self._vocab.get(field)
                        if by_len is not None:
                            terms = by_len.get(len(token))
                            if terms is not None:
                                terms.discard(token)

    # -- read path ----------------------------------------------------------

    def find_record_by_id(self, record_id: str) -> Optional[Record]:
        slot = self._id_to_slot.get(record_id)
        return self._docs[slot].record if slot is not None else None

    def find_candidate_matches(self, record: Record,
                               group_filtering: bool = False) -> List[Record]:
        should, must, must_not_slots = self._query_clauses(
            record, group_filtering
        )
        return self._do_query(should, must, must_not_slots)

    def _query_clauses(self, record: Record, group_filtering: bool):
        """Build the candidate query for one record: (should, must,
        must_not_slots) — shared by ``find_candidate_matches`` and the
        explain path so provenance can never drift from retrieval.

        fuzzy_search expands each token of a tokenized-comparator property
        into the indexed terms within 2 edits (transpositions counted, as
        in Lucene's FuzzyQuery automaton) — the reference's per-token
        FuzzyQuery (IncrementalLuceneDatabase.java:308-326; Lucene
        default maxEdits=2), rewritten as a term disjunction.  Each
        original token stays ONE scoring group whatever its expansion, so
        enabling fuzzy never dilutes exact-match scores via coord.
        """
        fuzzy = self.tunables.fuzzy_search
        should: List[List[Tuple[str, str]]] = []  # groups of alternatives
        must: List[List[Tuple[str, str]]] = []
        for prop in self.schema.lookup_properties():
            values = record.get_values(prop.name)
            required = prop.lookup == Lookup.REQUIRED
            tokenized = bool(getattr(prop.comparator, "is_tokenized", False))
            for value in values:
                for token in analyze(value):
                    if fuzzy and tokenized:
                        alts = self._fuzzy_terms(prop.name, token)
                    else:
                        alts = [(prop.name, token)]
                    (must if required else should).append(alts)

        must_not_slots: Set[int] = set(
            self._postings.get((DELETED_PROPERTY_NAME, "true"), ())
        )
        if group_filtering:
            group_no = record.get_value(GROUP_NO_PROPERTY_NAME)
            if not group_no:
                raise ValueError(
                    f"The '{GROUP_NO_PROPERTY_NAME}' property was missing or empty!"
                )
            must_not_slots |= self._postings.get((GROUP_NO_PROPERTY_NAME, group_no), set())

        return should, must, must_not_slots

    def _fuzzy_terms(self, field: str, token: str) -> List[Tuple[str, str]]:
        """The query token plus indexed terms within 2 edits (OSA distance,
        so transpositions count one edit, as in Lucene's automaton).

        Scans only the +/-2-length vocabulary buckets, caches per
        (field, token) until the next commit, and caps the expansion at
        Lucene's 50-term rewrite limit.
        """
        key = (field, token)
        cached = self._fuzzy_cache.get(key)
        if cached is not None:
            return cached
        out = [(field, token)]
        by_len = self._vocab.get(field)
        if by_len:
            n = len(token)
            for length in range(max(1, n - 2), n + 3):
                terms = by_len.get(length)
                if not terms:
                    continue
                for term in sorted(terms):  # deterministic under the cap
                    if term != token and _osa_distance(term, token, 2) <= 2:
                        out.append((field, term))
                        if len(out) >= _MAX_FUZZY_EXPANSIONS:
                            break
                if len(out) >= _MAX_FUZZY_EXPANSIONS:
                    break
        self._fuzzy_cache[key] = out
        return out

    def _prepare_groups(self, should, must):
        """Dedup'd scoring groups + idf table + query norm, or None when
        the query is empty (shared by ``_do_query`` and explain)."""
        # dedup groups by their primary (exact) term, preserving order —
        # repeated tokens score once, exactly as set(clauses) did pre-fuzzy
        groups: List[List[Tuple[str, str]]] = []
        seen: Set[Tuple[str, str]] = set()
        for group in should + must:
            if group[0] not in seen:
                seen.add(group[0])
                groups.append(group)
        if not groups:
            return None

        n_docs = max(len(self._docs), 1)
        flat = {alt for group in groups for alt in group}
        idf = {
            clause: 1.0 + math.log(n_docs / (len(self._postings.get(clause, ())) + 1))
            for clause in flat
        }
        # norms over the primary terms: identical to the fuzzy-off query,
        # so expansion never rescales scores of exact matches
        query_norm = 1.0 / math.sqrt(
            sum(idf[g[0]] ** 2 for g in groups) or 1.0
        )
        return groups, idf, query_norm

    def _group_contrib(self, doc: _Doc, group, idf):
        """One scoring group's best contribution for one doc:
        (contribution, (field, token, freq) of the winning alternative).
        The ONE copy of the classic tf·idf²·fieldNorm term — retrieval
        scoring and explain provenance can never drift apart."""
        best = 0.0
        best_clause = None
        for field, token in group:
            counts = doc.field_tokens.get(field)
            if not counts:
                break  # same field for every alternative
            freq = counts.get(token, 0)
            if freq == 0:
                continue
            tf = math.sqrt(freq)
            field_norm = 1.0 / math.sqrt(doc.field_lengths[field])
            contrib = tf * (idf[(field, token)] ** 2) * field_norm
            if contrib > best:
                best = contrib
                best_clause = (field, token, freq)
        return best, best_clause

    def _do_query(self, should, must, must_not_slots) -> List[Record]:
        prepared = self._prepare_groups(should, must)
        if prepared is None:
            return []
        groups, idf, query_norm = prepared
        flat = {alt for group in groups for alt in group}

        # candidate doc set; a MUST group (REQUIRED lookup) is satisfied by
        # any of its fuzzy-expanded alternatives
        candidates: Set[int] = set()
        for clause in flat:
            candidates |= self._postings.get(clause, set())
        for group in must:
            group_slots: Set[int] = set()
            for alt in group:
                group_slots |= self._postings.get(alt, set())
            candidates &= group_slots
        candidates -= must_not_slots
        if not candidates:
            return []

        scored: List[Tuple[float, int]] = []
        for slot in candidates:
            doc = self._docs[slot]
            score = 0.0
            matched = 0
            for group in groups:
                best, _ = self._group_contrib(doc, group, idf)
                if best > 0.0:
                    matched += 1
                    score += best
            coord = matched / len(groups)
            scored.append((score * coord * query_norm, slot))

        # adaptive limit loop (IncrementalLuceneDatabase.java:386-392): the
        # in-memory search is exhaustive, so "retrying with a larger limit"
        # reduces to growing the cut-off exactly as the reference would.
        # Only the adaptive limit is ever consumed, so top-limit selection
        # (heapq.nsmallest over the same (-score, slot) order the full sort
        # used — identical hits, identical ordering) keeps large candidate
        # sets at O(C log limit) instead of O(C log C); a grow-and-retry
        # re-selects, which is the rare case by the estimator's design
        rank = lambda s: (-s[0], s[1])  # noqa: E731 - shared sort/select key
        max_hits = self.tunables.max_search_hits
        thislimit = min(self._estimator.limit, max_hits)
        while True:
            if thislimit >= len(scored):
                hits = sorted(scored, key=rank)
                break
            hits = heapq.nsmallest(thislimit, scored, key=rank)
            if len(hits) < thislimit or thislimit == max_hits:
                break
            # clamp: ``x5`` from an estimator limit that does not divide
            # max_hits used to skip OVER the cap and grow until the whole
            # candidate set returned — both more hits than max_search_hits
            # permits and a terminal full sort on exactly the large
            # candidate sets the top-limit selection exists for
            thislimit = min(thislimit * 5, max_hits)

        # the reference iterates every returned hit down to min_relevance —
        # max_search_hits caps the *search*, not the match list
        matches: List[Record] = []
        for score, slot in hits:
            if score < self.tunables.min_relevance:
                break
            matches.append(self._docs[slot].record)

        if hits:
            self._estimator.record_result(len(matches))
        return matches

    def explain_retrieval(self, record: Record, candidate: Record,
                          group_filtering: bool = False) -> Dict:
        """Retrieval provenance for one (query, candidate) pair (ISSUE 5):
        which analyzed terms of the query's lookup properties hit the
        candidate's indexed fields, with the same tf·idf²·fieldNorm
        contributions, coord and query norm the live query applies —
        built on the exact clause/scoring helpers
        ``find_candidate_matches`` uses.  Side-effect free: the adaptive
        result estimator is never fed from here.
        """
        should, must, must_not_slots = self._query_clauses(
            record, group_filtering
        )
        out: Dict = {
            "mode": "inverted-index",
            "min_relevance": self.tunables.min_relevance,
        }
        slot = self._id_to_slot.get(candidate.record_id)
        if slot is None:
            out["candidate_indexed"] = False
            return out
        out["candidate_indexed"] = True
        out["excluded"] = slot in must_not_slots  # deleted / same group
        prepared = self._prepare_groups(should, must)
        if prepared is None:
            out.update(score=0.0, terms=[], retrieved=False)
            return out
        groups, idf, query_norm = prepared
        doc = self._docs[slot]
        terms = []
        matched = 0
        raw_score = 0.0
        for group in groups:
            best, clause = self._group_contrib(doc, group, idf)
            if best > 0.0 and clause is not None:
                matched += 1
                raw_score += best
                field, token, freq = clause
                terms.append({
                    "field": field,
                    "token": token,
                    "frequency": freq,
                    "idf": idf[(field, token)],
                    "contribution": best,
                    "fuzzy": token != group[0][1],
                    "required": group in must,
                })
        must_ok = all(
            any(slot in self._postings.get(alt, ()) for alt in group)
            for group in must
        )
        coord = matched / len(groups)
        score = raw_score * coord * query_norm
        out.update(
            terms=terms,
            groups=len(groups),
            matched_groups=matched,
            coord=coord,
            query_norm=query_norm,
            score=score,
            required_satisfied=must_ok,
            # the adaptive result limit can additionally cut a low-ranked
            # hit (EstimateResultTracker); this reports the score gate
            retrieved=(not out["excluded"] and must_ok and matched > 0
                       and score >= self.tunables.min_relevance),
        )
        return out

    def close(self) -> None:
        pass

    def __len__(self) -> int:
        # live indexed records: dukeDeleted rows stay resolvable by id but
        # are excluded from candidate search, so they don't count as
        # indexed.  O(1) counter — /stats reads this without the workload
        # lock, and an O(n) scan at 10M rows would stall ingest anyway.
        return self._live
