"""Host token inverted index — the Lucene-parity blocking backend.

Reproduces the candidate-retrieval semantics of the reference's
``IncrementalLuceneDatabase`` (IncrementalLuceneDatabase.java:57-592):

  * StandardAnalyzer-style analysis (lowercase word tokens, English stop
    words) for regular properties; identity-ish fields (ID, dukeDatasetId,
    dukeGroupNo, dukeOriginalEntityId) indexed as exact terms
    (NOT_ANALYZED, lines 543-548);
  * candidate query = OR over analyzed tokens of all lookup-property values
    (MUST when the property's lookup is REQUIRED), MUST_NOT on the query
    record's dukeGroupNo (record linkage) and on dukeDeleted=true
    (lines 459-492);
  * classic Lucene practical scoring (tf·idf²·fieldNorm·coord·queryNorm)
    with the ``min_relevance`` cut and ``max_search_hits`` cap;
  * the ``EstimateResultTracker`` adaptive result-limit estimation: limit
    starts at 100, retries ×5 while the result set fills the limit, ring
    buffer of the last 10 non-empty result sizes re-estimates the limit
    (lines 349-423; the copied comment calls this "the single biggest
    influence on search performance").  The reference's division-by-zero
    (NaN) when the first ring entry is zero (SURVEY.md quirk Q3) is fixed
    here by guarding the empty-window case;
  * Lucene-style visibility: records become searchable only at ``commit()``;
    re-indexing a record first deletes the previous copy by ID
    (lines 516-517).

Uncommitted (pending) operations and committed state are kept separate so
``Processor.deduplicate`` ordering — index all, commit, then query — behaves
exactly as with a real IndexWriter/IndexSearcher pair.
"""

from __future__ import annotations

import math
import re
from collections import Counter, defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ..core.config import DukeSchema, MatchTunables
from ..core.records import (
    DELETED_PROPERTY_NAME,
    GROUP_NO_PROPERTY_NAME,
    ID_PROPERTY_NAME,
    Lookup,
    Record,
)
from .base import CandidateIndex

# Lucene StandardAnalyzer's default English stop set
_STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)

_TOKEN_RE = re.compile(r"\w+", re.UNICODE)

# fields indexed as single exact terms (IncrementalLuceneDatabase.java:543-548)
_NOT_ANALYZED = frozenset(
    {ID_PROPERTY_NAME, "dukeDatasetId", GROUP_NO_PROPERTY_NAME, "dukeOriginalEntityId"}
)

SEARCH_EXPANSION_FACTOR = 1  # IncrementalLuceneDatabase.java:70


def analyze(value: str) -> List[str]:
    return [
        t for t in (m.group(0).lower() for m in _TOKEN_RE.finditer(value))
        if t not in _STOP_WORDS
    ]


class _Doc:
    __slots__ = ("slot", "record", "field_tokens", "field_lengths")

    def __init__(self, slot: int, record: Record):
        self.slot = slot
        self.record = record
        self.field_tokens: Dict[str, Counter] = {}
        self.field_lengths: Dict[str, int] = {}
        for prop in record.properties():
            tokens: List[str] = []
            for v in record.get_values(prop):
                if prop in _NOT_ANALYZED:
                    tokens.append(v)
                else:
                    tokens.extend(analyze(v))
            if tokens:
                self.field_tokens[prop] = Counter(tokens)
                self.field_lengths[prop] = len(tokens)


class _ResultEstimator:
    """EstimateResultTracker parity (IncrementalLuceneDatabase.java:359-423)."""

    def __init__(self):
        self.limit = 100
        self.prevsizes = [0] * 10
        self.sizeix = 0

    def record_result(self, size: int) -> None:
        self.prevsizes[self.sizeix] = size
        self.sizeix += 1
        if self.sizeix == len(self.prevsizes):
            self.sizeix = 0
            self.limit = max(int(self._average() * SEARCH_EXPANSION_FACTOR), self.limit)

    def _average(self) -> float:
        total = 0
        ix = 0
        while ix < len(self.prevsizes) and self.prevsizes[ix] != 0:
            total += self.prevsizes[ix]
            ix += 1
        if ix == 0:
            return 0.0  # reference would divide by zero here (quirk Q3)
        return total / ix


class InvertedIndex(CandidateIndex):
    def __init__(self, schema: DukeSchema, tunables: Optional[MatchTunables] = None):
        self.schema = schema
        self.tunables = tunables or MatchTunables()
        self._estimator = _ResultEstimator()
        self._indexing_disabled = False

        self._next_slot = 0
        self._docs: Dict[int, _Doc] = {}                # committed, by slot
        self._id_to_slot: Dict[str, int] = {}
        self._postings: Dict[Tuple[str, str], Set[int]] = defaultdict(set)
        self._pending: List[Tuple[str, object]] = []    # ("add", Record) | ("del", id)

    # -- write path ---------------------------------------------------------

    def index(self, record: Record) -> None:
        if self._indexing_disabled:
            return
        rid = record.record_id
        if rid is not None:
            self._pending.append(("del", rid))
        self._pending.append(("add", record))

    def delete(self, record: Record) -> None:
        rid = record.record_id
        if rid is not None:
            self._pending.append(("del", rid))

    def set_indexing_disabled(self, disabled: bool) -> None:
        self._indexing_disabled = disabled

    def commit(self) -> None:
        for op, payload in self._pending:
            if op == "del":
                self._remove_committed(payload)
            else:
                self._add_committed(payload)
        self._pending.clear()

    def _add_committed(self, record: Record) -> None:
        slot = self._next_slot
        self._next_slot += 1
        doc = _Doc(slot, record)
        self._docs[slot] = doc
        rid = record.record_id
        if rid is not None:
            self._id_to_slot[rid] = slot
        for field, counts in doc.field_tokens.items():
            for token in counts:
                self._postings[(field, token)].add(slot)

    def _remove_committed(self, record_id: str) -> None:
        slot = self._id_to_slot.pop(record_id, None)
        if slot is None:
            return
        doc = self._docs.pop(slot)
        for field, counts in doc.field_tokens.items():
            for token in counts:
                bucket = self._postings.get((field, token))
                if bucket is not None:
                    bucket.discard(slot)
                    if not bucket:
                        del self._postings[(field, token)]

    # -- read path ----------------------------------------------------------

    def find_record_by_id(self, record_id: str) -> Optional[Record]:
        slot = self._id_to_slot.get(record_id)
        return self._docs[slot].record if slot is not None else None

    def find_candidate_matches(self, record: Record,
                               group_filtering: bool = False) -> List[Record]:
        should: List[Tuple[str, str]] = []
        must: List[Tuple[str, str]] = []
        for prop in self.schema.lookup_properties():
            values = record.get_values(prop.name)
            required = prop.lookup == Lookup.REQUIRED
            for value in values:
                for token in analyze(value):
                    (must if required else should).append((prop.name, token))

        must_not_slots: Set[int] = set(
            self._postings.get((DELETED_PROPERTY_NAME, "true"), ())
        )
        if group_filtering:
            group_no = record.get_value(GROUP_NO_PROPERTY_NAME)
            if not group_no:
                raise ValueError(
                    f"The '{GROUP_NO_PROPERTY_NAME}' property was missing or empty!"
                )
            must_not_slots |= self._postings.get((GROUP_NO_PROPERTY_NAME, group_no), set())

        return self._do_query(should, must, must_not_slots)

    def _do_query(self, should, must, must_not_slots) -> List[Record]:
        clauses = should + must
        if not clauses:
            return []

        n_docs = max(len(self._docs), 1)
        idf = {
            clause: 1.0 + math.log(n_docs / (len(self._postings.get(clause, ())) + 1))
            for clause in set(clauses)
        }
        query_norm = 1.0 / math.sqrt(sum(idf[c] ** 2 for c in set(clauses)) or 1.0)

        # candidate doc set
        candidates: Set[int] = set()
        for clause in clauses:
            candidates |= self._postings.get(clause, set())
        for clause in must:
            candidates &= self._postings.get(clause, set())
        candidates -= must_not_slots
        if not candidates:
            return []

        scored: List[Tuple[float, int]] = []
        unique_clauses = set(clauses)
        for slot in candidates:
            doc = self._docs[slot]
            score = 0.0
            matched = 0
            for field, token in unique_clauses:
                counts = doc.field_tokens.get(field)
                if not counts:
                    continue
                freq = counts.get(token, 0)
                if freq == 0:
                    continue
                matched += 1
                tf = math.sqrt(freq)
                field_norm = 1.0 / math.sqrt(doc.field_lengths[field])
                score += tf * (idf[(field, token)] ** 2) * field_norm
            coord = matched / len(unique_clauses)
            scored.append((score * coord * query_norm, slot))
        scored.sort(key=lambda s: (-s[0], s[1]))

        # adaptive limit loop (IncrementalLuceneDatabase.java:386-392): the
        # in-memory search is exhaustive, so "retrying with a larger limit"
        # reduces to growing the cut-off exactly as the reference would
        max_hits = self.tunables.max_search_hits
        thislimit = min(self._estimator.limit, max_hits)
        while True:
            hits = scored[:thislimit]
            if len(hits) < thislimit or thislimit == max_hits:
                break
            thislimit *= 5

        # the reference iterates every returned hit down to min_relevance —
        # max_search_hits caps the *search*, not the match list
        matches: List[Record] = []
        for score, slot in hits:
            if score < self.tunables.min_relevance:
                break
            matches.append(self._docs[slot].record)

        if hits:
            self._estimator.record_result(len(matches))
        return matches

    def close(self) -> None:
        pass

    def __len__(self) -> int:
        return len(self._docs)
