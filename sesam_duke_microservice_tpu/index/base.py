"""Candidate-index (blocking database) interface.

The framework's equivalent of Duke's ``Database`` plugin point as the
reference subclasses it (IncrementalLuceneDatabase.java:57,459-492): index
records, answer candidate queries with group/deleted filtering, point-lookup
by id.  Implementations:

  * ``index.inverted.InvertedIndex`` — host token inverted index with
    Lucene-compatible semantics (min_relevance / max_search_hits / adaptive
    limit), the conformance backend;
  * ``engine.device_matcher.DeviceIndex`` — the TPU-native backend: corpus
    as HBM-resident padded token tensors, candidates via on-device n-gram
    prefilter + exact rescoring (no host round-trip per record).
"""

from __future__ import annotations

from typing import List, Optional

from ..core.records import Record


class CandidateIndex:
    def index(self, record: Record) -> None:
        """Add/replace a record (replaces any previous record with same ID)."""
        raise NotImplementedError

    def commit(self) -> None:
        """Make indexed records visible to subsequent queries."""
        raise NotImplementedError

    def find_record_by_id(self, record_id: str) -> Optional[Record]:
        raise NotImplementedError

    def find_candidate_matches(self, record: Record,
                               group_filtering: bool = False) -> List[Record]:
        """Candidate records for pair scoring.

        With ``group_filtering`` (record linkage), records sharing the
        query's ``dukeGroupNo`` are excluded; records flagged
        ``dukeDeleted=true`` are always excluded
        (IncrementalLuceneDatabase.java:467-478).
        """
        raise NotImplementedError

    def delete(self, record: Record) -> None:
        raise NotImplementedError

    def set_indexing_disabled(self, disabled: bool) -> None:
        """http-transform support (IncrementalLuceneDatabase.java:95-97)."""
        raise NotImplementedError

    def close(self) -> None:
        pass
