"""Ring-parallel corpus scoring: rotating query blocks over ppermute.

The ring-attention pattern applied to this workload's scaling axis
(SURVEY.md section 5.7 — "ring-structured pass of query blocks around the
mesh").  Where ``parallel.sharded`` replicates the whole query block to
every device and merges per-shard top-Ks with one ``all_gather``, the ring
scorer shards BOTH axes:

  * corpus feature tensors: record-axis sharded (as in parallel.sharded);
  * query block: ALSO sharded — each device starts with Q/D queries;
  * D ring steps: every device scores its resident query block against its
    local corpus shard, threading the block's accumulated global top-K
    through the scan (``ops.scoring.scan_topk(init=...)``), then
    ``ppermute``s the block + its carry to the next device.  After D hops
    each block has visited every shard and is back home with its global
    top-K — no all_gather, no replication.

Communication per step is O((Q/D) * (features + K)) point-to-point over
ICI — independent of corpus size and of D — while per-device compute and
query memory drop by 1/D versus the replicated layout.  The replicated
all_gather layout is the right default for service batches (queries are
small); the ring is the regime for *large* query blocks (bulk re-matching,
backfills, corpus-vs-corpus joins) where replicating Q feature tensors to
every device would dominate HBM or ICI.

Exactness: each (query, corpus-row) pair is scored by exactly one device
at exactly one step, and the carry merge is the same running-top-K the
single-device scan uses — results equal the single-device scorer
(tests/test_ring.py pins this on the virtual mesh).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import scoring as S
from .sharded import SHARD_AXIS, LeadingAxisPlacer


def build_ring_scorer(
    plan,
    mesh: Mesh,
    *,
    chunk: int = 512,
    top_k: int = 64,
    group_filtering: bool = False,
) -> Callable:
    """Ring variant of ``parallel.sharded.build_sharded_scorer``.

    Signature matches the sharded scorer, but ``qfeats``, ``query_group``
    and ``query_row`` must be sharded on the query (leading) axis with the
    total query count divisible by ``mesh.size``
    (``RingQueryPlacer.place`` does both), and the outputs come back
    query-axis sharded the same way.
    """
    pair_logits = S.build_pair_logits(plan)
    ndev = mesh.size
    perm = [(i, (i + 1) % ndev) for i in range(ndev)]

    shard_spec = P(SHARD_AXIS)
    repl = P()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(shard_spec, shard_spec, shard_spec, shard_spec,
                  shard_spec, shard_spec, shard_spec, repl),
        out_specs=(shard_spec, shard_spec, shard_spec),
        check_vma=False,
    )
    def score_ring(qfeats, corpus_feats, corpus_valid, corpus_deleted,
                   corpus_group, query_group, query_row, min_logit):
        local_cap = corpus_valid.shape[0]
        shard = lax.axis_index(SHARD_AXIS)
        row_offset = shard.astype(jnp.int32) * jnp.int32(local_cap)

        first = next(iter(qfeats.values()))
        qlocal = first["valid"].shape[0]
        carry_logit = jnp.full((qlocal, top_k), S.NEG_INF, jnp.float32)
        carry_index = jnp.full((qlocal, top_k), -1, jnp.int32)
        carry_count = jnp.zeros((qlocal,), jnp.int32)

        def rotate(a):
            return lax.ppermute(a, SHARD_AXIS, perm)

        qf, qg, qr = qfeats, query_group, query_row
        tl, ti, cnt = carry_logit, carry_index, carry_count
        # D is small and static: unroll the ring so each step's ppermute
        # can overlap the next step's compute under XLA's scheduler
        for step in range(ndev):
            tl, ti, cnt = S.scan_topk(
                pair_logits, qf, corpus_feats, corpus_valid,
                corpus_deleted, corpus_group, qg, qr, min_logit,
                chunk=chunk, top_k=top_k, group_filtering=group_filtering,
                row_offset=row_offset, init=(tl, ti, cnt),
            )
            if step + 1 < ndev:
                qf = jax.tree_util.tree_map(rotate, qf)
                qg, qr = rotate(qg), rotate(qr)
            # the carry rotates on EVERY hop (the last one brings each
            # block's top-K home); the query payload — the big per-hop
            # transfer — skips the final dead rotation
            tl, ti, cnt = rotate(tl), rotate(ti), rotate(cnt)
        return tl, ti, cnt

    return jax.jit(score_ring)


class RingQueryPlacer(LeadingAxisPlacer):
    """Places query-side arrays onto the mesh, query-axis sharded.

    Pads the query count up to a multiple of ``mesh.size`` (padding rows
    get ``query_row=-1`` / ``query_group=-2`` and score against nothing the
    caller keeps).
    """

    def __init__(self, mesh: Mesh):
        super().__init__(mesh, mesh.size)

    def place(self, qfeats, query_group: np.ndarray,
              query_row: np.ndarray):
        n = query_group.shape[0]
        cap = self.padded_capacity(n)
        feats = self._put_tree(qfeats, n, cap)
        group = self._put(query_group, n, cap, -2)
        row = self._put(query_row, n, cap, -1)
        return feats, group, row
