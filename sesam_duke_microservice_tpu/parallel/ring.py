"""Ring-parallel corpus scoring: rotating query blocks around the mesh.

The ring-attention pattern applied to this workload's scaling axis
(SURVEY.md section 5.7 — "ring-structured pass of query blocks around the
mesh").  Where ``parallel.sharded`` replicates the whole query block to
every device and merges per-shard top-Ks with one all-gather, the ring
scorer shards BOTH axes:

  * corpus feature tensors: record-axis sharded (as in parallel.sharded);
  * query block: ALSO sharded — each device starts with Q/D queries;
  * D ring steps: every device scores its resident query block against its
    local corpus shard, threading the block's accumulated global top-K
    through the scan (``ops.scoring.scan_topk(init=...)``), then rotates
    the block + its carry to the next device.  After D hops each block has
    visited every shard and is back home with its global top-K — no
    all_gather, no replication.

The rotation is expressed as ``jnp.roll(..., 1, axis=0)`` over the pinned
shard axis of a ``jit`` program — the partitioner lowers a roll of a
shard-axis-sharded array to the neighbor-to-neighbor collective-permute
the old hand-written ``ppermute`` spelled out.

Communication per step is O((Q/D) * (features + K)) point-to-point over
ICI — independent of corpus size and of D — while per-device compute and
query memory drop by 1/D versus the replicated layout.  The replicated
all_gather layout is the right default for service batches (queries are
small); the ring is the regime for *large* query blocks (bulk re-matching,
backfills, corpus-vs-corpus joins) where replicating Q feature tensors to
every device would dominate HBM or ICI.

Exactness: each (query, corpus-row) pair is scored by exactly one device
at exactly one step, and the carry merge is the same running-top-K the
single-device scan uses — results equal the single-device scorer
(tests/test_ring.py pins this on the virtual mesh).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from ..ops import scoring as S
from .sharded import (LeadingAxisPlacer, rule_sharding, shard_offsets,
                      shardwise)


def build_ring_scorer(
    plan,
    mesh: Mesh,
    *,
    chunk: int = 512,
    top_k: int = 64,
    group_filtering: bool = False,
) -> Callable:
    """Ring variant of ``parallel.sharded.build_sharded_scorer``.

    Signature matches the sharded scorer, but ``qfeats``, ``query_group``
    and ``query_row`` must be sharded on the query (leading) axis with the
    total query count divisible by ``mesh.size``
    (``RingQueryPlacer.place`` does both), and the outputs come back
    query-axis sharded the same way.
    """
    pair_logits = S.build_pair_logits(plan)
    ndev = mesh.size

    def pin(a):
        return lax.with_sharding_constraint(
            a, rule_sharding(mesh, "corpus", a.ndim))

    def rotate(a):
        # roll over the pinned shard axis == collective-permute
        # [(i, (i + 1) % D)]: device i+1 receives device i's block
        return pin(jnp.roll(a, 1, axis=0))

    def score_ring(qfeats, corpus_feats, corpus_valid, corpus_deleted,
                   corpus_group, query_group, query_row, min_logit):
        split = shardwise(mesh)
        cf = jax.tree_util.tree_map(split, corpus_feats)
        cv = split(corpus_valid)
        cd = split(corpus_deleted)
        cg = split(corpus_group)
        qf = jax.tree_util.tree_map(split, qfeats)
        qg = split(query_group)
        qr = split(query_row)
        local_cap = corpus_valid.shape[0] // ndev
        offsets = shard_offsets(mesh, local_cap)

        qlocal = query_group.shape[0] // ndev
        tl = pin(jnp.full((ndev, qlocal, top_k), S.NEG_INF, jnp.float32))
        ti = pin(jnp.full((ndev, qlocal, top_k), -1, jnp.int32))
        cnt = pin(jnp.zeros((ndev, qlocal), jnp.int32))

        def one_shard(cf, cv, cd, cg, row_offset, qf, qg, qr, tl, ti, cnt):
            return S.scan_topk(
                pair_logits, qf, cf, cv, cd, cg, qg, qr, min_logit,
                chunk=chunk, top_k=top_k, group_filtering=group_filtering,
                row_offset=row_offset, init=(tl, ti, cnt),
            )

        # D is small and static: unroll the ring so each step's rotation
        # can overlap the next step's compute under XLA's scheduler
        for step in range(ndev):
            tl, ti, cnt = jax.vmap(one_shard)(
                cf, cv, cd, cg, offsets, qf, qg, qr, tl, ti, cnt)
            if step + 1 < ndev:
                qf = jax.tree_util.tree_map(rotate, qf)
                qg, qr = rotate(qg), rotate(qr)
            # the carry rotates on EVERY hop (the last one brings each
            # block's top-K home); the query payload — the big per-hop
            # transfer — skips the final dead rotation
            tl, ti, cnt = rotate(tl), rotate(ti), rotate(cnt)

        def unsplit(a):
            return pin(jnp.reshape(a, (-1,) + a.shape[2:]))

        return unsplit(tl), unsplit(ti), unsplit(cnt)

    return jax.jit(score_ring)


class RingQueryPlacer(LeadingAxisPlacer):
    """Places query-side arrays onto the mesh, query-axis sharded.

    Pads the query count up to a multiple of ``mesh.size`` (padding rows
    get ``query_row=-1`` / ``query_group=-2`` and score against nothing the
    caller keeps).
    """

    def __init__(self, mesh: Mesh):
        super().__init__(mesh, mesh.size)

    def place(self, qfeats, query_group: np.ndarray,
              query_row: np.ndarray):
        n = query_group.shape[0]
        cap = self.padded_capacity(n)
        feats = self._put_tree(qfeats, n, cap)
        group = self._put(query_group, n, cap, -2)
        row = self._put(query_row, n, cap, -1)
        return feats, group, row
