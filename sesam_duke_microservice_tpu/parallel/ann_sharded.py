"""Mesh-sharded embedding-ANN scoring: retrieval + rescoring per shard,
merge over ICI.

Scale-out of the two-stage ANN program (``ops.scoring.build_ann_scorer``)
over a 1-D device mesh, following the same layout as the brute-force
sharded scorer (``parallel.sharded``): corpus tensors (including the
``ops.encoder`` embedding tree riding as a pseudo-property — the int8
scale vector shards with it) sharded on the record axis, queries
replicated.  Like the brute scorer, the program is a plain ``jit`` with
``with_sharding_constraint`` annotations — per-shard work is a ``vmap``
over the shard axis and the merge a constraint back to replicated layout,
with the partitioner inserting the collectives.

Per-shard work is fully local: cosine top-C over the local embedding rows
(one bf16 — or int8 x int8 -> int32 — matmul per chunk), then exact
rescoring of the local candidates — feature gathers never cross shards
(candidate rows are clipped into the shard's local range before the
gather, so each vmap lane only indexes its own slice).  Only the (Q, C)
scored results move: the replicated-layout constraint collects every
shard's (logit, global_row) pairs ((D, Q, C) — C is tiny) and each
device reduces them to the global top-C.  Communication is O(Q * C * D)
while compute scales 1/D — the candidate matrix never materializes
anywhere, matching the design target of SURVEY.md §5.7 (ring/allgather
sharded candidate retrieval at 10M-record scale, BASELINE.json
configs[4]).

IVF placement (ISSUE 9) follows the SNIPPETS.md pjit partition-rule
pattern — shard the big per-row state, replicate the small lookup
tables (``parallel.sharded.PARTITION_RULES``): the ``(nshards * K, B)``
cell-membership matrix of shard-LOCAL row ids is placed
``P(SHARD_AXIS)`` (each shard lane sees exactly its own (K, B) block)
while the tiny (K, D) centroid matrix rides replicated ``P()``.  Every
shard probes the same top-``nprobe`` cells (the replicated stage-1
matmul is identical everywhere) and scans only its local members of
those cells.

Because every shard keeps its own local top-C before the merge, the merged
candidate pool is a superset of the single-device pool (which keeps a
global top-C by cosine): sharding can only improve blocking recall, never
reduce it — asserted by ``tests/test_ann_sharded.py``.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import encoder as E
from ..ops import ivf as IVF
from ..ops import scoring as S
from .sharded import merge_topk, replicated, shard_offsets, shardwise


def _local_rescore(pair_logits, q_tree, qfeats, feats, emb_tree,
                   top_sim, top_index, row_offset, min_logit, *,
                   top_c: int):
    """Per-shard tail of both sharded ANN programs (runs inside the vmap
    lane): local exact rescoring of the shard's retrieved candidates
    (gathers never cross shards), plus the shared
    ``scoring.saturation_count`` predicate on the local count (a local
    top-C whose int8 cutoff band holds quantization-ambiguous candidates
    may have truncated a true candidate BEFORE the merge)."""
    retrieved = top_index >= 0
    local_rows = jnp.clip(top_index - row_offset, 0).reshape(-1)
    q = top_index.shape[0]
    cfeats = {
        prop: {
            name: jnp.take(arr, local_rows, axis=0).reshape(
                (q, top_c) + arr.shape[1:]
            )
            for name, arr in tensors.items()
        }
        for prop, tensors in feats.items()
    }
    logits = pair_logits(qfeats, cfeats)
    logits = jnp.where(retrieved, logits, S.NEG_INF)
    local_count = S.saturation_count(
        logits, top_sim, retrieved, min_logit,
        S.retrieval_amb_eps(q_tree, emb_tree),
    )
    return logits, top_index, local_count


def _merge(mesh, logits, top_index, local_count, min_logit, *, top_c: int):
    """Merge the vmapped (D, Q, C) per-shard results to the global top-C.

    The escalation signal must see BOTH truncation modes: a shard whose
    local top-C saturated (may have dropped above-bound rows before the
    merge — the max over shards of ``local_count``, the old ``pmax``), and
    a merged pool with more above-bound rows than the merge keeps (indices
    are unique across shards, so counting the merged pool counts each
    candidate once)."""
    repl = replicated(mesh)
    out_logit, out_index, merged_logit = merge_topk(mesh, logits, top_index, top_c)
    merged_above = (merged_logit > min_logit).sum(axis=1).astype(jnp.int32)
    count_sat = jnp.maximum(repl(local_count.max(axis=0)), merged_above)
    return out_logit, out_index, count_sat


def build_sharded_ann_scorer(
    plan,
    mesh,
    *,
    chunk: int = 512,
    top_c: int = 64,
    group_filtering: bool = False,
) -> Callable:
    """Like ``ops.scoring.build_ann_scorer`` but over a sharded corpus.

    Signature::

        fn(q_emb, qfeats, corpus_feats, corpus_valid, corpus_deleted,
           corpus_group, query_group, query_row, min_logit)
        -> (top_logit (Q, C), top_index (Q, C) global rows, count_sat (Q,))

    ``corpus_feats`` must include the ``ops.encoder.ANN_PROP`` embedding
    tree ({emb} bf16 or {emb, scale} int8) and be placed record-axis
    sharded (``ShardedCorpus``); queries are replicated.  ``count_sat``
    is the recall-escalation signal: the max of (a) any shard's local
    above-``min_logit`` count — widened by the int8 cosine-ambiguity
    credit — (a saturated local top-C may have truncated before the
    merge) and (b) the merged pool's above-bound count (the merge itself
    truncates when more than ``top_c`` survive).  The caller escalates
    when ``count_sat >= top_c``.
    """
    pair_logits = S.build_gathered_pair_logits(plan)
    ndev = mesh.size

    def score_shard(q_emb, qfeats, corpus_feats, corpus_valid,
                    corpus_deleted, corpus_group, query_group, query_row,
                    min_logit):
        split = shardwise(mesh)
        cf = jax.tree_util.tree_map(split, corpus_feats)
        cv = split(corpus_valid)
        cd = split(corpus_deleted)
        cg = split(corpus_group)
        local_cap = corpus_valid.shape[0] // ndev
        offsets = shard_offsets(mesh, local_cap)
        q_tree = E.as_emb_tree(q_emb)

        def one_shard(cf, cv, cd, cg, row_offset):
            emb_tree = E.as_emb_tree(cf[E.ANN_PROP])
            feats = {
                prop: tensors for prop, tensors in cf.items()
                if prop != E.ANN_PROP
            }
            # stage 1: local cosine top-C (global row ids via row_offset)
            top_sim, top_index = E.retrieval_scan(
                q_tree, emb_tree, cv, cd, cg, query_group, query_row,
                chunk=chunk, top_c=top_c, group_filtering=group_filtering,
                row_offset=row_offset,
            )
            return _local_rescore(
                pair_logits, q_tree, qfeats, feats, emb_tree, top_sim,
                top_index, row_offset, min_logit, top_c=top_c,
            )

        logits, top_index, local_count = jax.vmap(one_shard)(
            cf, cv, cd, cg, offsets)
        return _merge(mesh, logits, top_index, local_count, min_logit,
                      top_c=top_c)

    return jax.jit(score_shard)


def build_sharded_ivf_scorer(
    plan,
    mesh,
    *,
    top_c: int = 64,
    nprobe: int = 8,
    group_filtering: bool = False,
) -> Callable:
    """IVF cell-probe retrieval over the mesh (ISSUE 9).

    Signature (the sharded flat convention plus the two IVF tensors)::

        fn(q_emb, qfeats, corpus_feats, centroids, cell_rows,
           corpus_valid, corpus_deleted, corpus_group, query_group,
           query_row, min_logit) -> (top_logit, top_index, count_sat)

    ``centroids`` ride replicated; ``cell_rows`` is the stacked
    ``(mesh.size * K, B)`` shard-LOCAL membership matrix placed
    ``P(SHARD_AXIS)`` so each shard lane sees its own (K, B) block
    (``ops.ivf.IvfState`` builds exactly this layout).
    """
    pair_logits = S.build_gathered_pair_logits(plan)
    ndev = mesh.size
    slot_chunk = IVF.scan_slots()

    def score_shard(q_emb, qfeats, corpus_feats, centroids, cell_rows,
                    corpus_valid, corpus_deleted, corpus_group, query_group,
                    query_row, min_logit):
        split = shardwise(mesh)
        cf = jax.tree_util.tree_map(split, corpus_feats)
        cv = split(corpus_valid)
        cd = split(corpus_deleted)
        cg = split(corpus_group)
        rows = split(cell_rows)
        local_cap = corpus_valid.shape[0] // ndev
        offsets = shard_offsets(mesh, local_cap)
        q_tree = E.as_emb_tree(q_emb)

        def one_shard(cf, rows, cv, cd, cg, row_offset):
            emb_tree = E.as_emb_tree(cf[E.ANN_PROP])
            feats = {
                prop: tensors for prop, tensors in cf.items()
                if prop != E.ANN_PROP
            }
            top_sim, top_index = IVF.ivf_probe_topc(
                q_tree, emb_tree, centroids, rows, cv, cd, cg,
                query_group, query_row, top_c=top_c, nprobe=nprobe,
                slot_chunk=slot_chunk, group_filtering=group_filtering,
                row_offset=row_offset,
            )
            return _local_rescore(
                pair_logits, q_tree, qfeats, feats, emb_tree, top_sim,
                top_index, row_offset, min_logit, top_c=top_c,
            )

        logits, top_index, local_count = jax.vmap(one_shard)(
            cf, rows, cv, cd, cg, offsets)
        return _merge(mesh, logits, top_index, local_count, min_logit,
                      top_c=top_c)

    return jax.jit(score_shard)
