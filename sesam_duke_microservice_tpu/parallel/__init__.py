"""Mesh-parallel scale-out: sharded corpus scoring over ICI collectives.

The reference has no distributed backend of any kind (SURVEY.md section 2
component #16 — one JVM, one thread pool).  This package is its TPU-native
replacement, with two layouts for the corpus-axis scale-out sketched in
SURVEY.md section 5.7:

  * ``sharded`` / ``ann_sharded`` — corpus record-axis sharded across a
    ``jax.sharding.Mesh``, queries replicated; every device scores the
    block against its local shard and one ``all_gather`` merges the
    per-shard top-Ks.  The default for service-sized query batches.
  * ``ring`` — queries sharded too; blocks rotate around the mesh over
    ``ppermute`` carrying their running top-K (the ring-attention pattern
    on the corpus axis).  The regime for large query blocks, where
    replication would dominate HBM/ICI.

``multihost`` extends either mesh across hosts (jax.distributed over DCN).
"""

from .ann_sharded import build_sharded_ann_scorer
from .multihost import global_corpus_mesh, initialize as initialize_distributed
from .ring import RingQueryPlacer, build_ring_scorer
from .sharded import ShardedCorpus, build_sharded_scorer, corpus_mesh

__all__ = [
    "RingQueryPlacer",
    "ShardedCorpus",
    "build_ring_scorer",
    "build_sharded_ann_scorer",
    "build_sharded_scorer",
    "corpus_mesh",
    "global_corpus_mesh",
    "initialize_distributed",
]
