"""Mesh-parallel scale-out: sharded corpus scoring over ICI collectives.

The reference has no distributed backend of any kind (SURVEY.md section 2
component #16 — one JVM, one thread pool).  This package is its TPU-native
replacement: the corpus feature tensors are sharded across a
``jax.sharding.Mesh``, every device scores the replicated query block
against its local shard keeping a local top-K, and one ``all_gather`` over
the mesh axis merges the per-shard top-Ks into the global result — the
ring-structured candidate merge sketched in SURVEY.md section 5.7.
"""

from .ann_sharded import build_sharded_ann_scorer
from .multihost import global_corpus_mesh, initialize as initialize_distributed
from .sharded import ShardedCorpus, build_sharded_scorer, corpus_mesh

__all__ = [
    "ShardedCorpus",
    "build_sharded_ann_scorer",
    "build_sharded_scorer",
    "corpus_mesh",
    "global_corpus_mesh",
    "initialize_distributed",
]
