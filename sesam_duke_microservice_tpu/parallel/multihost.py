"""Multi-host scale-out: jax.distributed over DCN + mesh construction.

The reference's only "distribution" is the Sesam node HTTP-polling one
microservice container (SURVEY.md section 2 component #16).  The TPU-native
replacement for real scale is the standard JAX multi-controller model: one
Python process per host, ``jax.distributed.initialize`` over the
coordinator (DCN), and a global mesh whose record-sharding axis spans every
chip in the job.

Layout policy for this workload (corpus-sharded matching,
parallel/sharded.py + parallel/ann_sharded.py):

  * the corpus axis shards over ALL devices, hosts included — each chip
    holds ``N / total_chips`` rows and scores the replicated query block
    against them locally;
  * the only cross-device traffic is the per-shard top-K ``all_gather``
    ((D, Q, K), K tiny).  Within a slice it rides ICI; across slices the
    same collective rides DCN.  Because the merge payload is O(Q x K) per
    device — not O(corpus) — DCN bandwidth is not a bottleneck, so a flat
    1-D mesh is the right default (no need for the hierarchical
    ICI-inner/DCN-outer factorization a bandwidth-bound workload needs);
  * ingest is single-writer per workload (the service's lock discipline,
    SURVEY.md section 1 L5): the frontend host extracts features and
    ``device_put``s each shard slice; queries replicate.

``initialize()`` wraps ``jax.distributed.initialize`` with env-var
defaults (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) and
is a no-op for single-process runs, so the same entrypoint works on a
laptop, one TPU VM, or a multi-host slice job.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..telemetry.env import env_str

logger = logging.getLogger("multihost")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join (or skip) the multi-controller job; returns True if distributed.

    Arguments default from the standard env vars; when neither arguments
    nor env vars configure a coordinator, this is a single-process run and
    nothing happens (returns False).
    """
    import jax

    # idempotent: the service entrypoint initializes once, then every
    # sharded index construction calls through serving_mesh() again — a
    # second jax.distributed.initialize would raise ("must be called
    # before any JAX calls") because the first one already brought the
    # backend up
    from jax._src import distributed as _dist

    if _dist.global_state.client is not None:
        return jax.process_count() > 1

    coordinator_address = coordinator_address or env_str(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        # Cloud TPU multi-host jobs usually carry no explicit coordinator —
        # jax.distributed.initialize() auto-detects from the TPU/cluster
        # metadata.  Only attempt it when that metadata is plainly present,
        # so laptops/CI stay single-process without a failed probe.
        if any(env_str(v) for v in (
            "TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )):
            try:
                _enable_cpu_collectives()
                jax.distributed.initialize()
                logger.info(
                    "joined auto-detected distributed job: process %d/%d",
                    jax.process_index(), jax.process_count(),
                )
                return True
            except (RuntimeError, ValueError) as e:
                if ("before any JAX calls" in str(e)
                        or "coordinator_address" in str(e)):
                    # the backend is already up (tests, embedding apps) or
                    # the TPU metadata carries no coordinator (single-host
                    # axon) — normal single-process situations, not errors
                    logger.info(
                        "distributed auto-detect skipped (%s); continuing "
                        "single-process", e,
                    )
                else:
                    # coordinator unreachable / barrier timeout etc. also
                    # surface as RuntimeError — on a real multi-host job a
                    # silent local-only mesh would serve partial-corpus
                    # results, so keep the loud path
                    logger.exception(
                        "distributed auto-detect failed; continuing "
                        "single-process"
                    )
            except Exception:
                logger.exception(
                    "distributed auto-detect failed; continuing single-process"
                )
        return False
    kwargs = {"coordinator_address": coordinator_address}
    num_processes = num_processes or _int_env("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env(
        "JAX_PROCESS_ID"
    )
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    logger.info("jax.distributed.initialize(%s)", kwargs)
    _enable_cpu_collectives()
    jax.distributed.initialize(**kwargs)
    logger.info(
        "joined distributed job: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def _int_env(name: str) -> Optional[int]:
    raw = env_str(name)
    return int(raw) if raw and raw.isdigit() else None


def _enable_cpu_collectives() -> None:
    """XLA:CPU only runs cross-process programs through an explicit
    collectives layer; without one every multi-process computation —
    including ``device_put`` onto a global-mesh sharding — fails with
    "Multiprocess computations aren't implemented on the CPU backend".
    Select gloo before the distributed client comes up (the backend
    captures the option at client init).  TPU/GPU backends ignore it, so
    this is safe to set unconditionally for any distributed job."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as exc:  # pragma: no cover - jaxlib built without gloo
        logger.warning(
            "CPU collectives backend unavailable (%s); multi-process CPU "
            "meshes will not run", exc)


def global_corpus_mesh():
    """1-D corpus mesh over every device in the job (all hosts).

    Single-host this equals ``corpus_mesh()``; multi-host it spans the
    global device list, so the record axis shards across hosts and the
    top-K merge collective crosses DCN transparently.
    """
    import jax

    from .sharded import corpus_mesh

    return corpus_mesh(jax.devices())
