"""Sharded corpus scoring: constraint-driven GSPMD over a device mesh.

Data layout (the scaling-book recipe — pick a mesh, annotate shardings, let
XLA insert collectives):

  * corpus feature tensors: sharded along the record axis over mesh axis
    ``"shard"`` — each device holds ``capacity / n_devices`` rows in HBM;
  * query block: replicated — every device scores the same queries against
    its local rows (no query-side communication at all);
  * merge: each device's local top-K is constrained back to replicated
    layout ((D, Q, K) — K is tiny, so the all-gather XLA inserts moves
    Q*K*D*8 bytes over ICI, not the candidate matrix) and reduced to the
    global top-K on every device.

The program is a plain ``jit`` over ``NamedSharding``-placed inputs: the
per-shard scan is expressed as ``vmap`` over a leading shard axis pinned to
the mesh with ``with_sharding_constraint`` and the merge as a constraint to
replicated layout, so the partitioner — not a hand-written ``shard_map``
closure — chooses the collectives.  The partition rules per tensor family
live in ``PARTITION_RULES`` and are shared by the placement helpers here,
the in-program constraints, and the IVF placers in
``engine/sharded_matcher.py``.

This scales the O(Q x N) pair-scoring work linearly in device count while
the communication stays O(Q x K x D): the framework's counterpart of
ring-attention-style sequence parallelism for the corpus axis (SURVEY.md
section 5.7 — "sharded candidate retrieval").

The reference's single-JVM design has no equivalent (SURVEY.md section 2
rows 16-17); parity obligations stop at "same results as one device", which
``tests/test_parallel.py`` checks on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import scoring as S

SHARD_AXIS = "shard"

# Partition-rule table: tensor family -> leading-axis PartitionSpec maker.
# Record-carrying families shard their leading axis over the mesh; query-side
# and centroid tensors replicate.  Everything that places or constrains a
# tensor (LeadingAxisPlacer, the in-program constraints below, the IVF
# placers in engine/sharded_matcher.py) goes through this table so the
# layout contract lives in exactly one place.
PARTITION_RULES: Dict[str, Callable[[int], P]] = {
    # corpus feature tensors / embedding codes / int8 scales: record axis
    "corpus": lambda ndim: P(SHARD_AXIS, *([None] * (ndim - 1))),
    # IVF cell membership (stacked shard-local row-id matrix): record axis
    "ivf_membership": lambda ndim: P(SHARD_AXIS, *([None] * (ndim - 1))),
    # query block, thresholds, masks-of-queries: replicated
    "queries": lambda ndim: P(),
    # IVF centroids (and any other model-side small tensors): replicated
    "centroids": lambda ndim: P(),
}


def rule_sharding(mesh: Mesh, family: str, ndim: int) -> NamedSharding:
    """NamedSharding for ``family`` (a PARTITION_RULES key) at ``ndim``."""
    return NamedSharding(mesh, PARTITION_RULES[family](ndim))


def corpus_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices; the single sharding axis
    carries the corpus record dimension."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shardwise(mesh: Mesh):
    """(cap, ...) -> (ndev, cap/ndev, ...) with the leading axis pinned to
    the mesh.  The flat array is already record-axis sharded with a
    shard-granule-aligned capacity, so the reshape moves no data — it just
    exposes the shard axis for ``vmap``."""
    ndev = mesh.size

    def split(a):
        local = a.shape[0] // ndev
        r = jnp.reshape(a, (ndev, local) + a.shape[1:])
        return lax.with_sharding_constraint(
            r, NamedSharding(mesh, PARTITION_RULES["corpus"](r.ndim)))

    return split


def replicated(mesh: Mesh):
    """Constrain to replicated layout; XLA inserts the all-gather."""
    def repl(a):
        return lax.with_sharding_constraint(a, NamedSharding(mesh, P()))

    return repl


def shard_offsets(mesh: Mesh, local_cap) -> jnp.ndarray:
    """Per-shard global row offset, one element resident per device."""
    offs = jnp.arange(mesh.size, dtype=jnp.int32) * jnp.int32(local_cap)
    return lax.with_sharding_constraint(
        offs, NamedSharding(mesh, P(SHARD_AXIS)))


def merge_topk(mesh: Mesh, top_logit, top_index, top_k: int):
    """Reduce per-shard (D, Q, K) candidates to the global top-K.

    The transpose/reshape ordering (shard 0's K entries first, then shard
    1's, ...) matches the historical all_gather merge, so ``lax.top_k``'s
    stable tie-breaking-by-position yields the same winners.
    """
    repl = replicated(mesh)
    ndev, q = top_logit.shape[0], top_logit.shape[1]
    merged_logit = repl(jnp.transpose(top_logit, (1, 0, 2)).reshape(q, ndev * top_k))
    merged_index = repl(jnp.transpose(top_index, (1, 0, 2)).reshape(q, ndev * top_k))
    out_logit, sel = lax.top_k(merged_logit, top_k)
    out_index = jnp.take_along_axis(merged_index, sel, axis=1)
    return out_logit, out_index, merged_logit


def build_sharded_scorer(
    plan,
    mesh: Mesh,
    *,
    chunk: int = 512,
    top_k: int = 64,
    group_filtering: bool = False,
) -> Callable:
    """Like ``ops.scoring.build_corpus_scorer`` but over a sharded corpus.

    Input contract matches the single-device scorer, except the ``corpus_*``
    arrays must have their leading (record) axis divisible by
    ``mesh.size * chunk`` and be placed with ``ShardedCorpus`` (record-axis
    sharded).  Row indices in ``top_index`` and ``query_row`` are global.
    """
    pair_logits = S.build_pair_logits(plan)
    ndev = mesh.size

    def score_shard(qfeats, corpus_feats, corpus_valid, corpus_deleted,
                    corpus_group, query_group, query_row, min_logit):
        split = shardwise(mesh)
        repl = replicated(mesh)
        cf = jax.tree_util.tree_map(split, corpus_feats)
        cv = split(corpus_valid)
        cd = split(corpus_deleted)
        cg = split(corpus_group)
        local_cap = corpus_valid.shape[0] // ndev
        offsets = shard_offsets(mesh, local_cap)

        def one_shard(cf, cv, cd, cg, row_offset):
            return S.scan_topk(
                pair_logits, qfeats, cf, cv, cd, cg,
                query_group, query_row, min_logit,
                chunk=chunk, top_k=top_k, group_filtering=group_filtering,
                row_offset=row_offset,
            )

        top_logit, top_index, count = jax.vmap(one_shard)(cf, cv, cd, cg, offsets)
        out_logit, out_index, _ = merge_topk(mesh, top_logit, top_index, top_k)
        total_count = repl(count.sum(axis=0))
        return out_logit, out_index, total_count

    return jax.jit(score_shard)


def build_replicated_gather(mesh: Mesh) -> Callable:
    """Gather corpus rows from record-axis-sharded tensors into a compact
    replicated layout.

    ``rows`` is a flat vector of global (non-negative) row ids; the result
    tree holds ``(len(rows), ...)`` arrays constrained to replicated layout,
    so XLA inserts the cross-shard gather and every device ends up with the
    full survivor block.  This is the bridge that lets the sharded backends
    reuse the single-device ``build_dd_rescorer`` program bit-identically:
    gather the resolved block's (Q, K) survivors here, then rescore with an
    identity ``top_index``.
    """
    repl = replicated(mesh)

    @jax.jit
    def gather(cfeats, rows):
        return jax.tree_util.tree_map(
            lambda a: repl(jnp.take(a, rows, axis=0)), cfeats)

    return gather


class LeadingAxisPlacer:
    """Shared placement machinery: pad the leading axis to ``granule``
    multiples and device_put with leading-axis sharding over the mesh.

    Base for ``ShardedCorpus`` (record axis, granule = mesh.size * chunk)
    and ``parallel.ring.RingQueryPlacer`` (query axis, granule =
    mesh.size) — one copy of the padding/sharding conventions.  The
    shardings come from ``PARTITION_RULES["corpus"]``.
    """

    def __init__(self, mesh: Mesh, granule: int):
        self.mesh = mesh
        self.granule = granule
        self._sharding_cache: Dict[int, NamedSharding] = {}

    def padded_capacity(self, size: int) -> int:
        g = self.granule
        return max(g, ((size + g - 1) // g) * g)

    def _sharding(self, ndim: int) -> NamedSharding:
        if ndim not in self._sharding_cache:
            self._sharding_cache[ndim] = rule_sharding(self.mesh, "corpus", ndim)
        return self._sharding_cache[ndim]

    def _put(self, arr: np.ndarray, size: int, cap: int, fill=0):
        if arr.shape[0] != cap:
            out = np.full((cap,) + arr.shape[1:], fill, dtype=arr.dtype)
            out[:size] = arr[:size]
            arr = out
        return jax.device_put(arr, self._sharding(arr.ndim))

    def _put_tree(self, feats: Dict[str, Dict[str, np.ndarray]],
                  size: int, cap: int):
        return {
            prop: {
                name: self._put(arr, size, cap)
                for name, arr in tensors.items()
            }
            for prop, tensors in feats.items()
        }


class ShardedCorpus(LeadingAxisPlacer):
    """Places host corpus arrays onto the mesh, record-axis sharded.

    The capacity is padded up to a multiple of ``mesh.size * chunk`` so
    every shard gets the same number of whole scan chunks (padding rows are
    ``valid=False`` and masked out by the scorer).
    """

    def __init__(self, mesh: Mesh, *, chunk: int = 512):
        super().__init__(mesh, mesh.size * chunk)
        self.chunk = chunk

    def place(self, feats: Dict[str, Dict[str, np.ndarray]],
              row_valid: np.ndarray, row_deleted: np.ndarray,
              row_group: np.ndarray):
        """Pad to the shard granule and device_put with record-axis sharding.

        Returns (feats, valid, deleted, group) as sharded device arrays.
        """
        size = row_valid.shape[0]
        cap = self.padded_capacity(size)
        dev_feats = self._put_tree(feats, size, cap)
        valid = self._put(row_valid, size, cap, False)
        deleted = self._put(row_deleted, size, cap, False)
        group = self._put(row_group, size, cap, -1)
        return dev_feats, valid, deleted, group
