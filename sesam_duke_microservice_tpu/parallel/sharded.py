"""Sharded corpus scoring: shard_map over a device mesh.

Data layout (the scaling-book recipe — pick a mesh, annotate shardings, let
XLA insert collectives):

  * corpus feature tensors: sharded along the record axis over mesh axis
    ``"shard"`` — each device holds ``capacity / n_devices`` rows in HBM;
  * query block: replicated — every device scores the same queries against
    its local rows (no query-side communication at all);
  * merge: each device's local top-K is ``all_gather``ed over ICI
    ((D, Q, K) — K is tiny, so the collective moves Q*K*D*8 bytes, not the
    candidate matrix) and reduced to the global top-K on every device.

This scales the O(Q x N) pair-scoring work linearly in device count while
the communication stays O(Q x K x D): the framework's counterpart of
ring-attention-style sequence parallelism for the corpus axis (SURVEY.md
section 5.7 — "sharded candidate retrieval").

The reference's single-JVM design has no equivalent (SURVEY.md section 2
rows 16-17); parity obligations stop at "same results as one device", which
``tests/test_parallel.py`` checks on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import scoring as S

SHARD_AXIS = "shard"


def corpus_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices; the single sharding axis
    carries the corpus record dimension."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (SHARD_AXIS,))


def build_sharded_scorer(
    plan,
    mesh: Mesh,
    *,
    chunk: int = 512,
    top_k: int = 64,
    group_filtering: bool = False,
) -> Callable:
    """Like ``ops.scoring.build_corpus_scorer`` but over a sharded corpus.

    Input contract matches the single-device scorer, except the ``corpus_*``
    arrays must have their leading (record) axis divisible by
    ``mesh.size * chunk`` and be placed with ``ShardedCorpus`` (record-axis
    sharded).  Row indices in ``top_index`` and ``query_row`` are global.
    """
    pair_logits = S.build_pair_logits(plan)
    ndev = mesh.size

    corpus_spec = P(SHARD_AXIS)
    repl = P()

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(repl, corpus_spec, corpus_spec, corpus_spec, corpus_spec,
                  repl, repl, repl),
        out_specs=(repl, repl, repl),
        # the scan carry starts from replicated zeros but becomes
        # shard-varying once per-shard corpus data folds in; skip the
        # varying-manual-axes typecheck rather than pcast every init
        check_vma=False,
    )
    def score_shard(qfeats, corpus_feats, corpus_valid, corpus_deleted,
                    corpus_group, query_group, query_row, min_logit):
        local_cap = corpus_valid.shape[0]
        shard = lax.axis_index(SHARD_AXIS)
        row_offset = shard.astype(jnp.int32) * jnp.int32(local_cap)

        top_logit, top_index, count = S.scan_topk(
            pair_logits, qfeats, corpus_feats, corpus_valid, corpus_deleted,
            corpus_group, query_group, query_row, min_logit,
            chunk=chunk, top_k=top_k, group_filtering=group_filtering,
            row_offset=row_offset,
        )

        # merge: (D, Q, K) gathered over ICI, reduced to global top-K
        all_logit = lax.all_gather(top_logit, SHARD_AXIS)   # (D, Q, K)
        all_index = lax.all_gather(top_index, SHARD_AXIS)
        q = top_logit.shape[0]
        merged_logit = jnp.transpose(all_logit, (1, 0, 2)).reshape(q, ndev * top_k)
        merged_index = jnp.transpose(all_index, (1, 0, 2)).reshape(q, ndev * top_k)
        out_logit, sel = lax.top_k(merged_logit, top_k)
        out_index = jnp.take_along_axis(merged_index, sel, axis=1)
        total_count = lax.psum(count, SHARD_AXIS)
        return out_logit, out_index, total_count

    return jax.jit(score_shard)


class LeadingAxisPlacer:
    """Shared placement machinery: pad the leading axis to ``granule``
    multiples and device_put with leading-axis sharding over the mesh.

    Base for ``ShardedCorpus`` (record axis, granule = mesh.size * chunk)
    and ``parallel.ring.RingQueryPlacer`` (query axis, granule =
    mesh.size) — one copy of the padding/sharding conventions.
    """

    def __init__(self, mesh: Mesh, granule: int):
        self.mesh = mesh
        self.granule = granule
        self._sharding_cache: Dict[int, NamedSharding] = {}

    def padded_capacity(self, size: int) -> int:
        g = self.granule
        return max(g, ((size + g - 1) // g) * g)

    def _sharding(self, ndim: int) -> NamedSharding:
        if ndim not in self._sharding_cache:
            spec = P(SHARD_AXIS, *([None] * (ndim - 1)))
            self._sharding_cache[ndim] = NamedSharding(self.mesh, spec)
        return self._sharding_cache[ndim]

    def _put(self, arr: np.ndarray, size: int, cap: int, fill=0):
        if arr.shape[0] != cap:
            out = np.full((cap,) + arr.shape[1:], fill, dtype=arr.dtype)
            out[:size] = arr[:size]
            arr = out
        return jax.device_put(arr, self._sharding(arr.ndim))

    def _put_tree(self, feats: Dict[str, Dict[str, np.ndarray]],
                  size: int, cap: int):
        return {
            prop: {
                name: self._put(arr, size, cap)
                for name, arr in tensors.items()
            }
            for prop, tensors in feats.items()
        }


class ShardedCorpus(LeadingAxisPlacer):
    """Places host corpus arrays onto the mesh, record-axis sharded.

    The capacity is padded up to a multiple of ``mesh.size * chunk`` so
    every shard gets the same number of whole scan chunks (padding rows are
    ``valid=False`` and masked out by the scorer).
    """

    def __init__(self, mesh: Mesh, *, chunk: int = 512):
        super().__init__(mesh, mesh.size * chunk)
        self.chunk = chunk

    def place(self, feats: Dict[str, Dict[str, np.ndarray]],
              row_valid: np.ndarray, row_deleted: np.ndarray,
              row_group: np.ndarray):
        """Pad to the shard granule and device_put with record-axis sharding.

        Returns (feats, valid, deleted, group) as sharded device arrays.
        """
        size = row_valid.shape[0]
        cap = self.padded_capacity(size)
        dev_feats = self._put_tree(feats, size, cap)
        valid = self._put(row_valid, size, cap, False)
        deleted = self._put(row_deleted, size, cap, False)
        group = self._put(row_group, size, cap, -1)
        return dev_feats, valid, deleted, group
