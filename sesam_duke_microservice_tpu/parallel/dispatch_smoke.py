"""Two-process dispatch smoke: the multi-host serving path on one machine.

Run as ``python -m sesam_duke_microservice_tpu.parallel.dispatch_smoke
<role> <coordinator>`` with role ``frontend`` or ``follower``; each
process gets one virtual CPU device, so the global corpus mesh spans the
two processes and every scoring pass crosses the process boundary
(all_gather over the loopback "DCN").  The frontend drives real workload
batches through the dispatcher exactly as the HTTP handlers would
(``__graft_entry__.dryrun_multichip`` uses this as its two-process mode;
``tests/test_multihost_serving.py`` covers the full HTTP surface).
"""

from __future__ import annotations

import json
import os
import sys

# virtual-CPU smoke: pin the platform before any computation — on axon
# hosts the sitecustomize hook imports jax at interpreter startup and the
# JAX_PLATFORMS env var alone is too late (utils.virtual_mesh docs); an
# axon-platform child would report process_count()==1 regardless of the
# joined coordination job and the dispatcher would refuse to start
from ..utils.virtual_mesh import force_cpu_platform

force_cpu_platform()

SMOKE_XML = """
<DukeMicroService>
  <Deduplication name="people" link-database-type="in-memory">
    <duke>
      <schema>
        <threshold>0.8</threshold>
        <property><name>NAME</name><comparator>levenshtein</comparator><low>0.1</low><high>0.95</high></property>
      </schema>
      <data-source class="io.sesam.dukemicroservice.IncrementalDeduplicationDataSource">
        <param name="dataset-id" value="crm"/>
        <column name="name" property="NAME"/>
      </data-source>
    </duke>
  </Deduplication>
</DukeMicroService>
"""


def main() -> None:
    import logging

    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    role = sys.argv[1]
    coordinator = sys.argv[2]
    process_id = 0 if role == "frontend" else 1

    from . import multihost

    assert multihost.initialize(
        coordinator_address=coordinator, num_processes=2,
        process_id=process_id,
    ), "smoke needs a 2-process distributed job"
    import jax

    print(f"SMOKE {role}: pc={jax.process_count()} devs="
          f"{jax.device_count()}", file=sys.stderr, flush=True)

    if role == "follower":
        from .dispatch import follower_main

        follower_main()
        print("SMOKE_FOLLOWER_OK", flush=True)
        return

    # the smoke harness seeds its own process env before create_app —
    # env WRITES for the child config, not knob reads
    os.environ["CONFIG_STRING"] = SMOKE_XML  # dukecheck: ignore[DK301] smoke-harness env write
    os.environ.setdefault("MIN_RELEVANCE", "0.05")  # dukecheck: ignore[DK301] smoke-harness env write
    from ..service.app import create_app
    from .dispatch import start_dispatcher

    app = create_app(backend="sharded-brute", persistent=False)
    dispatcher = start_dispatcher(app)
    try:
        wl = app.deduplications["people"]
        with wl.lock:
            # duplicates at (1,2): both scoring passes run over the
            # 2-process mesh in lockstep with the follower
            wl.process_batch("crm", [
                {"_id": "1", "name": "entity number one"},
                {"_id": "2", "name": "entity number one"},
                {"_id": "3", "name": "completely different"},
            ])
            wl.process_batch("crm", [{"_id": "1", "_deleted": True}])
            rows = wl.links_since(0)
        live = [r for r in rows if not r["_deleted"]]
        retracted = [r for r in rows if r["_deleted"]]
        assert not live and len(retracted) == 1, rows
        assert {retracted[0]["entity1"], retracted[0]["entity2"]} == {"1", "2"}
        print("SMOKE_FRONTEND_OK " + json.dumps(len(rows)), flush=True)
    finally:
        dispatcher.close()
        app.close()


if __name__ == "__main__":
    main()
