"""Multi-host serving: single-controller dispatch of mesh operations.

The reference wires its matcher directly into the HTTP handlers of one JVM
(App.java:343-345,1005); SURVEY.md section 5.8 defines the TPU-native
scale-out as a single-controller dispatch model over the JAX collective
stack.  This module is that model's control plane:

  * **Frontend** (process 0): serves the full REST surface and owns every
    host-side subsystem — ingest, link databases, listeners, durable
    stores, feeds.  Each mesh-touching operation (a corpus commit, a
    scoring pass) is broadcast to the followers *before* the frontend
    executes it.
  * **Followers** (process 1..N-1): no HTTP, no link state — each runs a
    replica of every workload's sharded index (corpus host mirror + the
    jitted shard_map programs) and replays the frontend's operation
    stream in order, entering the same device programs in lockstep so the
    ``all_gather``/``psum`` collectives rendezvous across hosts
    (ICI within a slice, DCN across — parallel/multihost.py).

Correctness rests on two invariants:

  1. **Bit-identical host mirrors.**  In the multi-controller model each
     process supplies its local shards of every global array from its own
     host corpus mirror, so the mirrors must match across processes
     exactly.  Followers bootstrap from the frontend's corpus state (the
     snapshot wire format of ``DeviceIndex.snapshot_save`` plus the
     record mirror) and then apply the same deterministic mutations in
     the same order (op ``commit``).
  2. **Identical device-program order.**  XLA executes each process's
     programs in dispatch order; collectives deadlock if two processes
     enqueue the same programs in different orders.  The frontend holds
     ``Dispatcher.op_lock`` across every broadcast+execute section
     (serializing across workloads), and followers replay the single op
     stream sequentially.  Escalation re-runs (``resolve_block``) are
     driven by replicated device outputs, so every process makes the same
     widening decision at the same point — including the double-buffered
     dispatch order of ``DeviceProcessor`` (the follower runs the same
     loop structure via ``_score_blocks``).

The op channel is a plain length-prefixed-pickle TCP stream from the
frontend to each follower, opened only after a fixed-format raw-bytes
join handshake (no pickle ever touches unauthenticated bytes); the
frontend's address is published through the jax.distributed coordination
KV store (rendezvous only — the data path never rides the coordinator).  A dead follower surfaces as a hung
collective, the standard JAX multi-controller failure mode; the service
logs the follower set at startup so operators can correlate.

Every REST operation is supported multi-host, including the ring
re-match (r4): its query-sharded outputs materialize through
``process_allgather`` — a collective the follower replay enters in
lockstep (engine/rematch.py).
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import telemetry
from ..telemetry.env import env_flag, env_float, env_int, env_str
from ..utils import faults, lockcheck

logger = logging.getLogger("dispatch")

# rendezvous key in the jax.distributed coordination service KV store
_KV_ADDR_KEY = "sesam_duke/dispatch/addr"
_CONNECT_TIMEOUT_S = env_float("DUKE_DISPATCH_TIMEOUT", 600.0)

# Per-follower send discipline (ISSUE 8): every sendall is bounded by a
# timeout (a dead follower mid-bootstrap used to park the leader on a
# full send buffer forever), and transient failures retry with
# exponential backoff + jitter before the follower is EVICTED — the
# group degrades to the survivors instead of latching the whole slice.
_SEND_TIMEOUT_S = env_float("DUKE_DISPATCH_SEND_TIMEOUT", 120.0)
_SEND_RETRIES = env_int("DUKE_DISPATCH_SEND_RETRIES", 4)
_RETRY_BASE_S = env_float("DUKE_DISPATCH_RETRY_BASE_MS", 50.0) / 1000.0


def _backoff_delay(attempt: int) -> float:
    from ..utils.backoff import full_jitter_delay

    return full_jitter_delay(attempt, _RETRY_BASE_S, 2.0)

# Cached registry children (dukecheck DK501/DK502): op tags are a small
# closed set, so each child resolves through the family lock at most once
# per process; the per-op broadcast/replay paths then write plain
# single-writer child instruments.
_OP_CHILDREN: Dict[str, object] = {}
_REPLAY_CHILDREN: Dict[str, object] = {}
_BYTES_CHILD = telemetry.DISPATCH_BYTES.single()


def _op_child(tag: str):
    child = _OP_CHILDREN.get(tag)
    if child is None:
        # once per tag per process — init-time resolution, cached below
        child = telemetry.DISPATCH_OPS.labels(op=tag)  # dukecheck: ignore[DK501] once per op tag, cached
        _OP_CHILDREN[tag] = child
    return child


def _replay_child(tag: str):
    child = _REPLAY_CHILDREN.get(tag)
    if child is None:
        child = telemetry.FOLLOWER_REPLAY_SECONDS.labels(op=tag)  # dukecheck: ignore[DK501] once per op tag, cached
        _REPLAY_CHILDREN[tag] = child
    return child


_DISPATCHER: Optional["Dispatcher"] = None


def current() -> Optional["Dispatcher"]:
    """The active frontend dispatcher, or None (single-process serving and
    follower processes both see None — the broadcast hooks no-op)."""
    return _DISPATCHER


import contextlib


@contextlib.contextmanager
def latch_on_failure(d: Optional["Dispatcher"], reason_prefix: str):
    """THE post-broadcast execution guard: once an op has been broadcast,
    a frontend that fails to execute it locally leaves followers ahead on
    the op stream (mirror divergence, or un-matched collective programs)
    — so any exception latches the dispatcher before propagating, and
    every further mesh op refuses loudly instead of hanging a desynced
    collective.  ``d=None`` (single-process) passes exceptions through
    untouched.  One helper, used by every broadcast site (commit / score
    / rematch), so the invariant cannot drift between them."""
    if d is None:
        yield
        return
    try:
        yield
    except BaseException as e:
        d.mark_failed(f"{reason_prefix}: {e!r}")
        raise


# -- wire format -------------------------------------------------------------

# Join handshake: a FIXED-FORMAT raw-bytes frame — magic + sha256 hexdigest
# of the join token — sent by the follower before anything else.  The
# frontend authenticates this frame with hmac.compare_digest BEFORE any
# pickle ever touches bytes from the socket: unpickling attacker bytes is
# arbitrary code execution, so the pickle op stream begins strictly after
# authentication (advisor r4).  Hashing the token keeps the frame
# fixed-length for any operator-chosen DUKE_DISPATCH_TOKEN.
_HELLO_MAGIC = b"SDMT1"
# magic + sha256 hexdigest (ascii) + 8-byte big-endian follower index.
# The index rides the AUTHENTICATED frame so the leader's per-follower
# identity (fault-spec coordinates, eviction logs) is the follower's
# stable process index, not TCP accept order — accept order varies
# run-to-run with >1 follower, which would break DUKE_FAULTS site
# determinism (`partition=1:...` must mean process 2 in every run).
_HELLO_TOKEN_LEN = len(_HELLO_MAGIC) + 64
_HELLO_LEN = _HELLO_TOKEN_LEN + 8

# Commit digest handshake: after replaying each ("commit", ...) op the
# follower answers with ONE raw frame — magic + ok byte + its 32-byte
# chained mirror digest (DeviceIndex._mirror_digest), followed by a
# 4-byte length-prefixed tracing blob (the replay's remote spans as
# JSON, ISSUE 2; empty when no trace context rode the op) — and the
# frontend compares the digest against its own before releasing the op
# lock.  This makes asymmetric commit failures (a swallowed replay
# exception, follower OOM, a nondeterministic bug) halt the job at the
# very commit that diverged, instead of hanging a later collective or
# finalizing wrong top-K links off a stale mirror.  Raw bytes (JSON for
# the span blob), never pickle, so the response path stays as dumb as
# the hello frame.  The magic is SDMD2 (was SDMD1 before the span blob
# existed) and is checked BEFORE the length prefix is read, so a
# mixed-version mesh halts with a protocol error instead of blocking on
# bytes the other side will never send.
_DIGEST_MAGIC = b"SDMD2"
_DIGEST_LEN = len(_DIGEST_MAGIC) + 1 + 32
# a corrupt/hostile length prefix must not allocate unbounded memory on
# the frontend; real span blobs are a few KB (TRACE_MAX_SPANS-capped)
_SPAN_BLOB_MAX = 4 << 20

# Streamed bootstrap granularity: snapshot bytes per message / records per
# message.  Bounds BOTH sides' transient memory (frontend pickle frame,
# follower assembly) to O(chunk) regardless of corpus scale.
_SNAP_CHUNK = env_int("DUKE_DISPATCH_SNAP_CHUNK", 16 << 20)
_REC_BATCH = env_int("DUKE_DISPATCH_REC_BATCH", 2048)


def _digest_frame(ok: bool, digest: bytes, spans: bytes = b"") -> bytes:
    payload = digest if len(digest) == 32 else bytes(32)
    if len(spans) > _SPAN_BLOB_MAX:
        spans = b""  # never let an oversized trace wedge the handshake
    return (_DIGEST_MAGIC + (b"\x01" if ok else b"\x00") + payload
            + struct.pack(">I", len(spans)) + spans)


def _verify_enabled() -> bool:
    return env_flag("DUKE_DISPATCH_VERIFY", True)


def _hello_frame(token: str, idx: int = 0) -> bytes:
    import hashlib

    return (_HELLO_MAGIC
            + hashlib.sha256(token.encode()).hexdigest().encode()
            + struct.pack(">Q", idx))


def with_trace_ctx(op: tuple) -> tuple:
    """Append the active trace context to a mesh op (ISSUE 2): followers
    replay it as remote child spans of the leader's request trace.  No
    active trace (startup, bootstrap streaming) appends nothing — the op
    keeps its historical shape and followers see no context."""
    tc = telemetry.tracing.propagation_context()
    return op if tc is None else op + (tc,)


def _op_trace_ctx(op: tuple, index: int) -> Optional[dict]:
    """The optional trailing trace context of a replayed op (see
    ``with_trace_ctx``)."""
    if len(op) > index and isinstance(op[index], dict):
        return op[index]
    return None


def _join_token() -> Optional[str]:
    """Operator-provided pre-shared secret, if any.  Set on BOTH sides it
    replaces the per-run random token, which is what makes the
    DUKE_DISPATCH_ADDR rendezvous bypass actually usable (a follower
    outside the coordination service can never learn a random token)."""
    return env_str("DUKE_DISPATCH_TOKEN") or None


# Op frame header: payload length, leadership epoch, per-follower frame
# sequence number.  The epoch fences zombie ex-leaders (a follower
# rejects ops from an epoch lower than the one it has adopted); the
# sequence number makes the stream idempotent under duplicate delivery
# (the retry/fault layer may send a frame twice — the follower drops
# seq <= last) and LOUD under loss (a gap means this follower missed an
# op the leader believes delivered; its replica must resync, so the
# loop raises instead of serving a hole).
_HDR = struct.Struct(">QIQ")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("dispatch channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_op(sock: socket.socket):
    """One framed op off the dispatch stream: (op, epoch, frame_seq)."""
    n, epoch, seq = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return pickle.loads(_recv_exact(sock, n)), epoch, seq


def _recv_msg(sock: socket.socket):
    """The next op alone — for test/bench followers that don't exercise
    the epoch/seq fencing."""
    return _recv_op(sock)[0]


def _kv_client():
    """The jax.distributed coordination-service KV client (private API —
    isolated here so an upstream rename breaks exactly one function; the
    DUKE_DISPATCH_ADDR env var bypasses it entirely)."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "jax.distributed is not initialized (multi-host dispatch needs "
            "the coordination service, or set DUKE_DISPATCH_ADDR)"
        )
    return client


def _env_fingerprint() -> dict:
    """Shape-relevant configuration that must match across processes (a
    mismatch would compile different programs → collective deadlock)."""
    import jax

    from ..engine import device_matcher as DM

    return {
        "jax": jax.__version__,
        "devices": jax.device_count(),
        "chunk": DM._CHUNK,
        "buckets": DM._QUERY_BUCKETS,
        "update_slice": DM._UPDATE_SLICE,
        "value_slots_max": DM._VALUE_SLOTS_MAX,
        "initial_top_k": DM._INITIAL_TOP_K,
        "ann_dim": env_str("DEVICE_ANN_DIM", "256"),
        "ann_c": env_str("DEVICE_ANN_CANDIDATES", "64"),
        # retrieval-program knobs: one-sided settings lower DIFFERENT
        # shard_map programs (fused Pallas kernel vs XLA scan, different
        # bin/recall shapes) whose cross-host all_gather would deadlock
        "ann_fused": env_str("DEVICE_ANN_FUSED", "1"),
        "ann_seg": env_str("DEVICE_ANN_SEG", "64"),
        "ann_exact": env_str("DEVICE_ANN_EXACT_TOPK", "0"),
        "ann_recall": env_str("DEVICE_ANN_RECALL_TARGET", "0.99"),
        "ann_chunk": env_str("DEVICE_ANN_RETRIEVAL_CHUNK", "65536"),
        # every env knob that sizes a feature tensor (ops.features): a
        # mismatch here compiles different-shape programs per process and
        # deadlocks the first cross-host collective
        "max_chars": env_str("DEVICE_MAX_CHARS", ""),
        "max_chars_cap": env_str("DEVICE_MAX_CHARS_CAP", ""),
        "demote_chars": env_str("DEVICE_DEMOTE_CHARS", ""),
        "max_grams": env_str("DEVICE_MAX_GRAMS", ""),
        "max_tokens": env_str("DEVICE_MAX_TOKENS", ""),
        "value_slots": env_str("DEVICE_VALUE_SLOTS", ""),
        # not shape-relevant, but a one-sided setting deadlocks the
        # digest handshake (unread frames fill the follower's send
        # buffer), so enforce agreement at bootstrap
        "verify": _verify_enabled(),
    }


# -- frontend ----------------------------------------------------------------


class _Follower:
    """Per-follower health + stream state (ISSUE 8): one entry per
    accepted connection.  ``alive`` flips false on eviction; ``seq`` is
    the per-follower frame sequence number (frames successfully sent)."""

    __slots__ = ("idx", "conn", "peer", "alive", "seq")

    def __init__(self, idx: int, conn: socket.socket, peer="?"):
        self.idx = idx
        self.conn = conn
        self.peer = peer
        self.alive = True
        self.seq = 0


class Dispatcher:
    """Frontend-side op broadcaster (process 0 of a multi-host job)."""

    def __init__(self, app, epoch: int = 1):
        self.app = app
        # leadership epoch, stamped into every frame header: followers
        # reject ops from a lower epoch, so a zombie ex-leader's stale
        # broadcasts can never corrupt a promoted group (ISSUE 8)
        self.epoch = epoch
        # serializes every broadcast+execute section across workloads so
        # all processes enqueue device programs in one global order
        self.op_lock = threading.RLock()
        self._send_lock = threading.Lock()
        # single-writer: the accept loop (startup, pre-broadcast) appends;
        # broadcast-time iteration snapshots under self._send_lock and
        # eviction only flips per-entry alive flags
        self._followers: List[_Follower] = []
        self._op_index = 0  # broadcast ordinal (fault-plan coordinates)
        self._server: Optional[socket.socket] = None
        self._closed = False
        # latched only on a FRONTEND-side desync: an op was broadcast
        # but the frontend failed to execute it locally, so followers
        # are ahead on a stream that is not replayable
        # (latch_on_failure).  Per-FOLLOWER failures no longer latch —
        # they evict that follower and the group degrades to the
        # survivors (_evict).  Recovery from the latch = restart.
        self._failed: Optional[str] = None

    @property
    def _conns(self) -> List[socket.socket]:
        """Live follower connections (kept as the historical name — a
        swath of tests wires loopback followers through it)."""
        return [f.conn for f in self._followers if f.alive]

    @_conns.setter
    def _conns(self, conns: List[socket.socket]) -> None:
        self._followers = [_Follower(i, c) for i, c in enumerate(conns)]

    def live_followers(self) -> List[_Follower]:
        return [f for f in self._followers if f.alive]

    # - lifecycle -

    def start(self) -> None:
        import secrets

        import jax

        n_followers = jax.process_count() - 1
        if n_followers <= 0:
            raise RuntimeError("Dispatcher.start() needs a multi-process job")
        bind_host = env_str("DUKE_DISPATCH_BIND", "0.0.0.0")
        advertise = env_str("DUKE_DISPATCH_HOST")
        port = env_int("DUKE_DISPATCH_PORT", 0)
        self._server = socket.create_server((bind_host, port))
        actual_port = self._server.getsockname()[1]
        if advertise is None:
            advertise = socket.gethostname()
        # join token: a pre-shared DUKE_DISPATCH_TOKEN when the operator
        # set one, else per-run random, published only through the
        # coordination-service KV store — so a follower slot requires the
        # secret or coordination-service access; an arbitrary process that
        # can reach the TCP port cannot claim a slot (and receive the
        # bootstrap's record payload) or starve the real followers out of
        # theirs.  The handshake is raw bytes (_hello_frame): nothing from
        # an unauthenticated socket is ever unpickled.
        psk = _join_token()
        token = psk or secrets.token_hex(16)
        addr = f"{advertise}:{actual_port}"
        # a pre-shared secret is long-lived (reused across runs), so it
        # must never widen into the KV store's trust boundary — publish
        # the address alone and let followers supply the secret from
        # their own env (a per-run random token, by contrast, is exactly
        # the thing the KV rendezvous exists to distribute)
        _kv_client().key_value_set(
            _KV_ADDR_KEY, addr if psk else f"{addr}/{token}"
        )
        logger.info(
            "dispatch: waiting for %d follower(s) on %s", n_followers, addr
        )
        self._accept_followers(n_followers, token)
        self._tag_workloads(self.app.deduplications, self.app.record_linkages)
        self._bootstrap_followers()
        telemetry.DISPATCH_EPOCH.set(self.epoch)  # dukecheck: ignore[DK502] once: dispatcher start
        global _DISPATCHER
        _DISPATCHER = self

    def _accept_followers(self, n_followers: int, token: str) -> None:
        """Accept exactly ``n_followers`` authenticated connections.

        Authentication reads a FIXED-LENGTH raw frame and compares it in
        constant time — pickle.loads never sees bytes from a socket that
        has not presented the join token (unpickling attacker-controlled
        bytes is arbitrary code execution, advisor r4 high)."""
        import hmac

        expected_token = _hello_frame(token)[:_HELLO_TOKEN_LEN]
        self._server.settimeout(_CONNECT_TIMEOUT_S)
        while len(self._followers) < n_followers:
            conn, peer = self._server.accept()
            try:
                conn.settimeout(30.0)
                hello = _recv_exact(conn, _HELLO_LEN)
                if not hmac.compare_digest(hello[:_HELLO_TOKEN_LEN],
                                           expected_token):
                    raise ValueError("bad join token")
                conn.settimeout(None)
            except Exception as e:
                logger.warning(
                    "dispatch: rejected connection from %s (%s)", peer, e
                )
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # the AUTHENTICATED frame's trailing index is the follower's
            # stable identity (process index - 1), independent of accept
            # order — fault-spec coordinates and eviction logs use it
            (idx,) = struct.unpack(">Q", hello[_HELLO_TOKEN_LEN:])
            if any(f.idx == idx for f in self._followers):
                logger.warning(
                    "dispatch: duplicate follower index %d from %s "
                    "(misconfigured JAX_PROCESS_ID?)", idx, peer,
                )
            self._followers.append(_Follower(idx, conn, peer))
            telemetry.DISPATCH_FOLLOWERS.set(len(self._followers))  # dukecheck: ignore[DK502] rare event: follower join
            logger.info("dispatch: follower connected from %s", peer)

    def _bootstrap_followers(self) -> None:
        self.broadcast((
            "bootstrap_begin",
            self.app.backend,
            self.app.config_string,
            _env_fingerprint(),
        ))
        self._stream_states(self.app.deduplications, self.app.record_linkages)
        self.broadcast(("bootstrap_end",))

    def close(self) -> None:
        global _DISPATCHER
        if self._closed:
            return
        self._closed = True
        try:
            self.broadcast(("shutdown",))
        except Exception:
            pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        telemetry.DISPATCH_FOLLOWERS.set(0)  # dukecheck: ignore[DK502] once: dispatcher shutdown
        if self._server is not None:
            self._server.close()
        if _DISPATCHER is self:
            _DISPATCHER = None

    # - ops -

    def broadcast(self, op: tuple) -> None:
        """Send one op to every LIVE follower (in one global order).

        Per-follower health (ISSUE 8): a send failure no longer latches
        the dispatcher.  Transient failures retry with exponential
        backoff + jitter; a follower that stays unreachable is EVICTED
        (``duke_follower_evictions_total``) and the group degrades to
        the survivors.  Only a frontend-side desync (``mark_failed`` via
        ``latch_on_failure``) still halts every mesh op."""
        if self._failed is not None:
            raise RuntimeError(
                "multi-host dispatch is down (frontend desynced from its "
                f"own op stream: {self._failed}); restart the job to "
                "recover"
            )
        data = pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL)
        tag = str(op[0])
        self._op_index += 1
        plan = faults.active()
        if plan is not None:
            plan.check_leader_crash(self._op_index)
        live = self.live_followers()
        # Dispatch observability (ISSUE 1 item 4), with two deliberate
        # substitutions: (a) there is no "dispatch queue depth" series
        # because broadcast is a synchronous sendall under op_lock — no
        # queue exists; backpressure surfaces as duke_ingest_queue_depth
        # (requests waiting on the workload lock behind the op in
        # flight).  (b) per-SHARD score time would need a device sync
        # per shard (forbidden on the scoring path); the per-HOST proxy
        # is duke_follower_replay_seconds{op="score"} vs the frontend's
        # duke_engine_phase_seconds{phase="retrieve"}.
        _op_child(tag).inc()
        _BYTES_CHILD.inc((_HDR.size + len(data)) * len(live))
        # lockcheck visibility: which locks are held across this blocking
        # network broadcast (the mesh op lock is expected; anything else
        # in the DUKE_LOCKCHECK=1 report deserves a look)
        lockcheck.note_blocking("dispatch.broadcast")
        with self._send_lock:
            for f in live:
                self._send_frame(f, tag, data, plan)

    @staticmethod
    def _send_tracked(conn: socket.socket, frame: bytes) -> None:
        """``sendall`` with a byte cursor: an ``OSError`` is re-raised
        carrying how much of the frame hit the wire (``e.frame_sent``),
        so the caller can tell a retry-safe failure (0 bytes — the
        stream is still frame-aligned) from a torn frame."""
        sent = 0
        try:
            while sent < len(frame):
                sent += conn.send(frame[sent:])
        except OSError as e:
            e.frame_sent = sent
            raise

    def _send_frame(self, f: _Follower, tag: str, data: bytes,
                    plan) -> bool:
        """One framed send to one follower, with bounded retry +
        exponential backoff + jitter before eviction.

        Only failures with ZERO bytes of the frame on the wire are
        retried — injected pre-send faults, and real socket errors whose
        first ``send`` wrote nothing (connection reset noticed at write
        time), where the stream is still frame-aligned.  After a partial
        write the stream position is torn, so the only safe recovery is
        eviction.  The frame seq advances per successful send; a
        fault-injected dup re-sends the SAME seq, which the follower
        drops."""
        err: Optional[BaseException] = None
        attempts = 0
        while True:
            header = _HDR.pack(len(data), self.epoch, f.seq + 1)
            try:
                if plan is not None:
                    plan.before_send(tag, f.idx, self._op_index, attempts)
                f.conn.settimeout(_SEND_TIMEOUT_S)
                try:
                    self._send_tracked(f.conn, header + data)
                    f.seq += 1
                    if plan is not None and plan.dup_send(
                            tag, f.idx, self._op_index):
                        # chaos dup rides the SAME seq; it must never
                        # re-enter the retry loop (the primary send
                        # already advanced f.seq, so a "retry" would
                        # mint a fresh seq for duplicate payload)
                        try:
                            self._send_tracked(f.conn, header + data)
                        except OSError as e:
                            if getattr(e, "frame_sent", 0):
                                self._evict(f, f"dup send tore: {e!r}")
                                return False
                            # zero bytes: the optional dup just didn't
                            # happen; the stream is intact
                finally:
                    try:
                        f.conn.settimeout(None)
                    except OSError:
                        pass
                return True
            except faults.InjectedSendFailure as e:
                err = e
            except OSError as e:
                if getattr(e, "frame_sent", 0) or isinstance(
                        e, socket.timeout):
                    # bytes of a torn frame are on the wire (or a
                    # 120 s-stalled peer — retrying a full send buffer
                    # just stalls again): the stream cannot recover
                    self._evict(f, f"send failed: {e!r}")
                    return False
                err = e  # zero bytes sent: frame-aligned, retry safe
            attempts += 1
            if attempts > _SEND_RETRIES:
                self._evict(
                    f, f"{attempts} send attempts failed: {err!r}"
                )
                return False
            time.sleep(_backoff_delay(attempts))

    def _evict(self, f: _Follower, reason: str) -> None:
        """Remove one follower from the serving group (idempotent): its
        stream is torn or it stopped answering, so it can never catch up
        on the non-replayable op stream — but the SURVIVORS can keep
        serving, so the dispatcher stays up (``duke_dispatch_down``
        stays 0) and only the eviction counter moves."""
        if not f.alive:
            return
        f.alive = False
        try:
            f.conn.close()
        except OSError:
            pass
        telemetry.FOLLOWER_EVICTIONS.inc()  # dukecheck: ignore[DK502] rare event: follower eviction
        survivors = len(self.live_followers())
        telemetry.DISPATCH_FOLLOWERS.set(survivors)  # dukecheck: ignore[DK502] rare event: follower eviction
        logger.error(
            "dispatch: evicted follower %d at %s (%s); serving degrades "
            "to %d survivor(s)%s",
            f.idx, f.peer, reason, survivors,
            "" if survivors else
            " — single-process serving until the job re-forms",
        )
        backend = getattr(self.app, "backend", None)
        if backend in ("sharded", "sharded-brute"):
            # the eviction keeps the op stream and replica read tier
            # alive, but THIS mesh's jitted collectives still span the
            # evicted host's devices: entering the next cross-host
            # scoring program would hang forever inside the collective
            # (holding the workload + op locks), not fail.  Latch mesh
            # ops loudly instead — a RuntimeError per request beats an
            # unbounded wedge; restart the job to re-form the mesh.
            self.mark_failed(
                f"follower {f.idx} evicted from a {backend} mesh "
                f"({reason}); cross-host collectives cannot run without "
                "it"
            )

    def verify_mirror_digest(self, key, digest: bytes) -> None:
        """Read one digest frame per live follower for the commit just
        applied and compare against the frontend's own chained mirror
        digest (``DeviceIndex._fold_mirror_digest``).  A mismatch,
        replay failure, or dead/slow follower EVICTS that follower — its
        mirror is permanently behind/diverged, but the frontend's own
        state is authoritative and the survivors are still in lockstep,
        so the commit stands and serving degrades instead of latching
        (ISSUE 8; the pre-HA behavior latched the whole slice).  Called
        with ``op_lock`` held (commit runs inside the broadcast+execute
        section), so frames can never interleave across commits."""
        if not _verify_enabled():
            return
        for f in self.live_followers():
            conn = f.conn
            try:
                conn.settimeout(_CONNECT_TIMEOUT_S)
                frame = _recv_exact(conn, _DIGEST_LEN)
                if frame[: len(_DIGEST_MAGIC)] != _DIGEST_MAGIC:
                    # wrong magic = mixed-version follower (or stream
                    # corruption): fail HERE, before blocking on a
                    # length prefix the other side never sends
                    raise EOFError(
                        f"bad digest-frame magic "
                        f"{frame[: len(_DIGEST_MAGIC)]!r} (mixed-version "
                        f"mesh? expected {_DIGEST_MAGIC!r})"
                    )
                (blob_len,) = struct.unpack(">I", _recv_exact(conn, 4))
                if blob_len > _SPAN_BLOB_MAX:
                    raise EOFError(
                        f"span blob length {blob_len} exceeds the "
                        f"{_SPAN_BLOB_MAX}-byte cap (corrupt frame?)"
                    )
                blob = _recv_exact(conn, blob_len) if blob_len else b""
            except (OSError, EOFError) as e:
                self._evict(
                    f, f"no commit digest for {key}: {e!r}"
                )
                continue
            finally:
                try:
                    conn.settimeout(None)
                except OSError:
                    pass
            # follower replay spans ride the handshake home: splice them
            # into the request's live trace (same trace id) so one tree
            # spans leader and followers (telemetry.tracing re-anchors
            # the follower's monotonic clock at graft time)
            telemetry.tracing.graft_remote(blob)
            # magic already validated above (mismatch raised pre-blob)
            ok = frame[len(_DIGEST_MAGIC)] == 1
            theirs = frame[len(_DIGEST_MAGIC) + 1:]
            if not ok or theirs != digest:
                self._evict(
                    f,
                    f"mirror diverged on commit for {key}: "
                    + ("replay failed" if not ok else
                       f"digest {theirs.hex()} != {digest.hex()}"),
                )

    def mark_failed(self, reason: str) -> None:
        """Latch the dispatcher down after an op-stream desync the sender
        detected OUTSIDE broadcast() (e.g. the frontend aborted mid-run
        after telling followers to run a full pass): every further mesh
        op raises instead of hanging on a desynced collective."""
        if self._failed is None:
            self._failed = reason
            telemetry.DISPATCH_DOWN.set(1)  # dukecheck: ignore[DK502] failure latch, fires at most once
            # connected-follower gauge drops to zero with the latch: the
            # mesh cannot serve another op, so a dashboard on the gauge
            # alone sees the outage (ROADMAP open item)
            telemetry.DISPATCH_FOLLOWERS.set(0)  # dukecheck: ignore[DK502] failure latch, fires at most once
            logger.error(
                "dispatch: halting mesh ops (%s) — restart the job", reason
            )

    def on_reload(self, sc, new_dedups: Dict, new_linkages: Dict) -> None:
        """Called by DukeApp.apply_config after building the replacement
        workloads (old locks held, nothing in flight): re-tags the new
        indexes and streams followers the new config + corpus states."""
        self._tag_workloads(new_dedups, new_linkages)
        self.broadcast(("reload_begin", self.app.backend, sc.config_string))
        self._stream_states(new_dedups, new_linkages)
        self.broadcast(("bootstrap_end",))

    # - helpers -

    def _tag_workloads(self, dedups: Dict, linkages: Dict) -> None:
        for kind, registry in (("deduplication", dedups),
                               ("recordlinkage", linkages)):
            for name, wl in registry.items():
                wl.index._dispatch_key = (kind, name)
                self._install_link_publisher((kind, name), wl)

    def _install_link_publisher(self, key, wl) -> None:
        """Wrap the workload's link database so every committed link
        batch (scoring matches, one-to-one retractions/rewrites, delete
        retractions — in arrival order) broadcasts as a first-class
        ``links`` op; followers fold them into replica link DBs and
        serve ``?since=`` feeds locally (ISSUE 8 tentpole)."""
        from ..links.replica import PublishingLinkDatabase

        if isinstance(wl.link_database, PublishingLinkDatabase):
            return  # already wrapped (re-tag after reload of same wl)

        def publish(seq: int, rows) -> None:
            self.broadcast(("links", key, seq, rows))

        wl.replace_link_database(
            PublishingLinkDatabase(wl.link_database, publish)
        )

    def _stream_states(self, dedups: Dict, linkages: Dict) -> None:
        for kind, registry in (("deduplication", dedups),
                               ("recordlinkage", linkages)):
            for name, wl in registry.items():
                self._stream_state((kind, name), wl.index,
                                   getattr(wl, "link_database", None))

    def _stream_state(self, key, index, link_db=None) -> None:
        """Stream one workload's corpus bootstrap in O(chunk)-bounded
        messages: the snapshot wire format file-chunked, the record
        mirror in batches — never a whole-corpus pickle (the r4 payload
        was one message holding snapshot bytes + every Record; at the 10M
        flagship scale that is a ~10+ GB frame).  Bounded-memory resume
        is the reference's own stance — its restart is an on-disk index
        open (IncrementalLuceneDatabase.java:233-244)."""
        has_snapshot = (getattr(index, "corpus", None) is not None
                        and index.corpus.size > 0)
        self.broadcast(("state_begin", key, {
            "has_snapshot": has_snapshot,
            # followers chain their commit digests from the frontend's
            # captured point, so the handshake compares equal iff every
            # post-bootstrap commit applied identically on both sides
            "mirror_digest": index._mirror_digest,
            # replica link DBs resume the published link stream from the
            # publisher's sequence at this capture point — the streamed
            # link_state rows below ARE the state at that watermark
            "link_seq": getattr(link_db, "seq", 0),
        }))
        if link_db is not None:
            # bootstrap the replica link DB: every current row (asserted
            # AND retracted — the replica must serve the full ?since=
            # history semantics), batched like the record mirror.
            # get_all_links drains any write-behind buffer first, so the
            # rows match the link_seq watermark captured above.
            from ..links.replica import encode_link

            batch: List = []
            for link in link_db.get_all_links():
                batch.append(encode_link(link))
                if len(batch) >= _REC_BATCH:
                    self.broadcast(("link_state", key, batch))
                    batch = []
            if batch:
                self.broadcast(("link_state", key, batch))
        if has_snapshot:
            fd, tmp = tempfile.mkstemp(suffix=".npz")
            os.close(fd)
            try:
                index.snapshot_save(tmp)
                with open(tmp, "rb") as f:
                    while True:
                        chunk = f.read(_SNAP_CHUNK)
                        if not chunk:
                            break
                        self.broadcast(("snap", key, chunk))
            finally:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            batch: List = []
            # bulk_values streams the store's cursor directly (bounded
            # memory AND no per-id SELECT); plain-dict mirrors walk
            # values() — either way this loop holds O(_REC_BATCH) records
            values = getattr(index.records, "bulk_values",
                             index.records.values)
            for record in values():
                batch.append(record)
                if len(batch) >= _REC_BATCH:
                    self.broadcast(("recs", key, batch))
                    batch = []
            if batch:
                self.broadcast(("recs", key, batch))
        self.broadcast(("state_end", key))


# -- follower ----------------------------------------------------------------


class FollowerProcessor:
    """Device-program replayer for one workload replica: the scoring side
    of ``DeviceProcessor`` with host finalization off.  It deliberately
    reuses ``DeviceProcessor._score_blocks`` so the dispatch order
    (double-buffered blocks, escalation re-runs) is the frontend's
    bit-for-bit — drift there deadlocks collectives (invariant 2)."""

    def __init__(self, schema, index, *, group_filtering: bool):
        from ..engine.device_matcher import DeviceProcessor

        self._proc = DeviceProcessor(
            schema, index, group_filtering=group_filtering
        )
        self._proc.finalize_survivors = False

    def score(self, records) -> None:
        self._proc._score_blocks(records)


class _StateAssembly:
    """Follower-side accumulator for one workload's streamed bootstrap:
    snapshot chunks append to a temp file, record batches land in a local
    SQLite store — O(chunk) transient memory at any corpus scale."""

    def __init__(self, key, meta: dict):
        import shutil

        self.key = key
        self.meta = meta
        self.dir = tempfile.mkdtemp(prefix="duke-follower-")
        self._rm = shutil.rmtree
        self.snap_path = os.path.join(self.dir, "bootstrap.npz")
        self._snap_f = (open(self.snap_path, "wb")
                        if meta["has_snapshot"] else None)
        if meta["has_snapshot"]:
            from ..store.records import SqliteRecordStore

            self.store = SqliteRecordStore(
                os.path.join(self.dir, "records.db")
            )
        else:
            self.store = None

    def add_snapshot_chunk(self, data: bytes) -> None:
        self._snap_f.write(data)

    def add_records(self, records) -> None:
        self.store.put_many(records)

    def finish(self) -> None:
        if self._snap_f is not None:
            self._snap_f.close()
            self._snap_f = None

    def discard(self) -> None:
        self.finish()
        if self.store is not None:
            self.store.close()
            self.store = None
        self._rm(self.dir, ignore_errors=True)


class _Replica:
    """One workload's follower-side state: sharded index + processor.

    The record mirror is a ``LazyRecordMap`` over the assembly's local
    SQLite store — the same bounded-memory mirror the frontend itself
    uses at the flagship scale, so neither side materializes the corpus.
    Commit replay keeps that store current (``apply_commit`` writes the
    batch store-first, mirroring Workload's persist-before-index order)
    — a LazyRecordMap write lands only in its bounded LRU, so skipping
    the store write would silently resurrect stale rows after eviction.
    """

    def __init__(self, sc, kind: str, name: str, backend: str,
                 asm: _StateAssembly):
        registry = (sc.deduplications if kind == "deduplication"
                    else sc.record_linkages)
        wc = registry[name]
        # backend-generic (ISSUE 8): production multi-host runs sharded
        # backends, but the HA machinery (replica link DBs, epoch
        # fencing, failover) is backend-agnostic — single-device
        # backends let the fault-injection suites run on hosts whose
        # jax lacks shard_map
        if backend == "sharded-brute":
            from ..engine.sharded_matcher import ShardedDeviceIndex

            self.index = ShardedDeviceIndex(wc.duke, tunables=sc.tunables)
        elif backend == "sharded":
            from ..engine.sharded_matcher import ShardedAnnIndex

            self.index = ShardedAnnIndex(wc.duke, tunables=sc.tunables)
        elif backend == "device":
            from ..engine.device_matcher import DeviceIndex

            self.index = DeviceIndex(wc.duke, tunables=sc.tunables)
        elif backend == "ann":
            from ..engine.ann_matcher import AnnIndex

            self.index = AnnIndex(wc.duke, tunables=sc.tunables)
        else:
            raise RuntimeError(
                f"follower replicas need a device-family backend "
                f"(got {backend!r})"
            )
        self.processor = FollowerProcessor(
            wc.duke, self.index, group_filtering=wc.is_record_linkage
        )
        self._asm = asm
        if asm.meta["has_snapshot"]:
            self._adopt(asm)
        # AFTER adoption: snapshot_load replays nothing through commit(),
        # so the digest chain starts exactly at the frontend's captured
        # point regardless of how the frontend's corpus got here
        self.index._mirror_digest = asm.meta["mirror_digest"]

    def _adopt(self, asm: _StateAssembly) -> None:
        import numpy as np

        from ..store.records import LazyRecordMap

        # trusted bootstrap from the live frontend: the content compare
        # is satisfied by the snapshot's own stamp (the staleness guard
        # protects restarts from DISK state; this state was streamed
        # from a quiesced live corpus seconds ago)
        with np.load(asm.snap_path) as data:
            content = str(data["__content"])
        if not self.index.snapshot_load(
            asm.snap_path, LazyRecordMap(asm.store), content_hash=content
        ):
            raise RuntimeError(
                "follower bootstrap: corpus state rejected (plan/env "
                "mismatch with the frontend?)"
            )
        # the snapshot served its one purpose; at the flagship scale it
        # is multi-GB per workload, so don't pin it for the replica's
        # lifetime (records.db stays — the lazy mirror reads through it)
        try:
            os.unlink(asm.snap_path)
        except OSError:
            pass

    def apply_commit(self, records) -> None:
        """Replay one commit op: local store first (the mirror reads
        through to it), then index + commit — the frontend's own order."""
        if self._asm.store is not None:
            self._asm.store.put_many(records)
        for record in records:
            self.index.index(record)
        self.index.commit()

    def close(self) -> None:
        self.index.close()
        self._asm.discard()


class _FollowerSession:
    """The follower's op-stream state machine, socket-free so tests can
    drive it op by op: ``handle(op)`` returns False on shutdown.
    ``send`` is the response channel (digest handshake frames).

    Framed transports route through ``handle_frame`` instead, which
    applies the HA stream discipline (ISSUE 8) before ``handle``:

      * **epoch fencing** — ops from an epoch lower than the adopted one
        are dropped (counted in ``stale_rejected``): after a promotion a
        zombie ex-leader's stale broadcasts can never corrupt the group;
      * **dup dropping** — a frame seq <= the last applied seq is the
        retry/fault layer re-sending a frame; applying it twice would
        double-apply a commit, so it drops silently;
      * **gap detection** — a seq skip means this follower missed an op
        the leader believes delivered (non-replayable stream), so the
        loop raises instead of serving a hole.
    """

    def __init__(self, send, follower_idx: int = 0):
        from ..core.config import parse_config

        self._parse_config = parse_config
        self._send = send
        self.follower_idx = follower_idx
        self.replicas: Dict[Tuple[str, str], _Replica] = {}
        # follower-side replica link DBs (ISSUE 8 tentpole): one per
        # workload, fed by the ``link_state`` bootstrap + ``links`` ops,
        # read concurrently by the replica HTTP read plane
        self.link_replicas: Dict[Tuple[str, str], object] = {}
        self._pending: Dict[Tuple[str, str], _StateAssembly] = {}
        self._pending_links: Dict[Tuple[str, str], List] = {}
        self._incoming: Optional[Tuple[str, str]] = None  # (backend, cfg)
        # stream fencing state (framed transports only)
        self.epoch = 0
        self.last_seq = 0
        self.stale_rejected = 0  # ops dropped from a fenced-out epoch
        self._op_count = 0  # ops handled (fault-plan coordinates)
        # promotion hand-over: the promoted app owns the replica indexes
        # and link DBs from then on, so close() must not release them
        self.promoted = False

    def adopt_epoch(self, epoch: int) -> None:
        """Raise the fencing epoch (promotion): frames still in flight
        from the deposed leader carry a lower epoch and are rejected."""
        self.epoch = max(self.epoch, epoch)

    def handle_frame(self, op: tuple, epoch: int, seq: int) -> bool:
        """One framed op with the HA stream discipline applied (see the
        class docstring); returns False on shutdown."""
        if epoch < self.epoch:
            self.stale_rejected += 1
            logger.warning(
                "follower: rejected %r op from fenced-out epoch %d "
                "(adopted epoch is %d) — zombie ex-leader?",
                op[0], epoch, self.epoch,
            )
            return True
        if epoch > self.epoch:
            # a higher epoch is a NEW leader's stream: adopt it and
            # restart the seq space at this frame
            self.epoch = epoch
            self.last_seq = seq - 1
        if seq <= self.last_seq:
            return True  # duplicate delivery (retry/fault layer): drop
        if seq != self.last_seq + 1:
            raise RuntimeError(
                f"dispatch stream gap: frame seq {seq} arrived after "
                f"{self.last_seq} (missed {seq - self.last_seq - 1} "
                "frame(s)); this follower must resync"
            )
        self.last_seq = seq
        return self.handle(op)

    def close(self) -> None:
        if not self.promoted:
            for replica in self.replicas.values():
                try:
                    replica.close()
                except Exception:
                    pass
        self.replicas.clear()
        self.link_replicas.clear()
        for asm in self._pending.values():
            asm.discard()
        self._pending.clear()
        self._pending_links.clear()

    def _begin(self, backend: str, config_string: str) -> None:
        # release old replicas (device memory) before new states stream
        for replica in self.replicas.values():
            replica.close()
        self.replicas.clear()
        self.link_replicas.clear()
        self._pending_links.clear()
        self._incoming = (backend, config_string)

    def handle(self, op: tuple) -> bool:
        self._op_count += 1
        plan = faults.active()
        if plan is not None and plan.follower_crash(self.follower_idx,
                                                    self._op_count):
            # injected hard death: the replay loop dies exactly like a
            # follower OOM/segv would — mid-stream, no farewell frame
            raise RuntimeError(
                f"injected follower crash at op {self._op_count} "
                "(DUKE_FAULTS crash_follower)"
            )
        t0 = time.monotonic()
        try:
            return self._handle(op)
        finally:
            # replay-lag visibility: how long each op class takes on this
            # follower (a follower consistently slower than the frontend
            # here is the one that will eventually stall a collective)
            _replay_child(str(op[0])).observe(time.monotonic() - t0)

    def _handle(self, op: tuple) -> bool:
        tag = op[0]
        if tag == "bootstrap_begin":
            _, backend, config_string, fingerprint = op
            mine = _env_fingerprint()
            if fingerprint != mine:
                raise RuntimeError(
                    "follower env/shape fingerprint mismatch vs "
                    f"frontend: {fingerprint} != {mine} — all processes "
                    "must run identical DEVICE_*/schema configuration"
                )
            self._begin(backend, config_string)
        elif tag == "reload_begin":
            _, backend, config_string = op
            self._begin(backend, config_string)
        elif tag == "state_begin":
            _, key, meta = op
            self._pending[key] = _StateAssembly(key, meta)
            self._pending_links[key] = []
        elif tag == "snap":
            _, key, data = op
            self._pending[key].add_snapshot_chunk(data)
        elif tag == "recs":
            _, key, records = op
            self._pending[key].add_records(records)
        elif tag == "link_state":
            # replica link DB bootstrap rows (ISSUE 8): the leader's full
            # link state at the captured ``link_seq`` watermark, batched
            _, key, rows = op
            self._pending_links[key].extend(rows)
        elif tag == "state_end":
            _, key = op
            asm = self._pending.pop(key)
            asm.finish()
            backend, config_string = self._incoming
            sc = self._parse_config(config_string)
            kind, name = key
            try:
                self.replicas[key] = _Replica(sc, kind, name, backend, asm)
            except BaseException:
                # the assembly left _pending but no replica owns it — a
                # rejected bootstrap must not leak its multi-GB temp dir
                # across a restart loop
                asm.discard()
                raise
            from ..links.replica import ReplicaLinkDatabase

            replica_db = ReplicaLinkDatabase()
            replica_db.load_snapshot(self._pending_links.pop(key, []),
                                     asm.meta.get("link_seq", 0))
            self.link_replicas[key] = replica_db
            # failover starts hot (ISSUE 15): warm the bootstrapped
            # replica's scorer ladder NOW — AOT deserialization plus
            # background miss-fill through the same path a cold start
            # uses — so an eventual promotion (adopt_workload's
            # processor re-runs the same no-op-when-warm call) serves
            # its first post-failover batches without first-contact
            # compile stalls
            cache = getattr(self.replicas[key].index, "scorer_cache",
                            None)
            if cache is not None:
                cache.prewarm_async(kind == "recordlinkage")
        elif tag == "links":
            # one committed link batch (scoring matches, retractions,
            # one-to-one rewrites — in the leader's arrival order): fold
            # into the replica under the monotonic watermark.  A
            # duplicate batch drops (idempotent); a GAP raises — the
            # frame-seq discipline upstream makes one impossible on a
            # framed transport, so a gap here means a buggy publisher
            # and the replica must never silently serve a hole.
            _, key, seq, rows = op[:4]
            db = self.link_replicas.get(key)
            if db is None:
                raise RuntimeError(
                    f"links op for {key} before its bootstrap link state"
                )
            db.note_head(seq)
            db.apply_ops(seq, rows)
        elif tag == "bootstrap_end":
            logger.info(
                "follower: %d workload replica(s) ready", len(self.replicas)
            )
        elif tag == "commit":
            # ops carry the leader's trace context as an optional trailing
            # element (ISSUE 2): the replay runs as a remote child span of
            # the leader's request trace and rides home in the digest frame
            _, key, records = op[:3]
            cap = telemetry.tracing.capture_remote(
                "follower:commit", _op_trace_ctx(op, 3),
                {"records": len(records), "process": "follower"},
            )
            try:
                with cap:
                    self.replicas[key].apply_commit(records)
            except Exception:
                # deterministic engine errors raise SYMMETRICALLY on the
                # frontend (same code, same inputs), so surviving them
                # keeps the mirrors consistent; dying here would let one
                # bad request wedge the whole mesh.  An asymmetric
                # (hardware) failure is caught by the digest handshake:
                # ok=False halts the frontend at this very commit.
                logger.exception("follower: commit replay failed")
                if _verify_enabled():
                    self._send(_digest_frame(False, b"", cap.wire()))
            else:
                # answer the frontend's digest handshake (one frame per
                # commit, read under the frontend's op lock).  Gated on
                # the SAME env flag the frontend reads (fingerprint-
                # checked at bootstrap): an unread frame per commit would
                # eventually fill the TCP buffer and deadlock the loop.
                if _verify_enabled():
                    self._send(_digest_frame(
                        True, self.replicas[key].index._mirror_digest,
                        cap.wire(),
                    ))
        elif tag == "score":
            _, key, records = op[:3]
            try:
                # no response channel on score ops: the replay span lands
                # in the follower's LOCAL flight recorder (same trace id
                # as the leader's tree) instead of shipping back
                with telemetry.tracing.capture_remote(
                    "follower:score", _op_trace_ctx(op, 3),
                    {"records": len(records), "process": "follower"},
                    recorder=telemetry.tracing.RECORDER,
                ):
                    self.replicas[key].processor.score(records)
            except Exception:
                logger.exception("follower: score replay failed")
        elif tag == "rematch":
            _, key, block_rows = op[:3]
            from ..engine.rematch import replay_rematch

            replica = self.replicas[key]
            try:
                with telemetry.tracing.capture_remote(
                    "follower:rematch", _op_trace_ctx(op, 3),
                    {"process": "follower"},
                    recorder=telemetry.tracing.RECORDER,
                ):
                    replay_rematch(replica.index, replica.processor._proc,
                                   query_block_rows=block_rows)
            except Exception:
                logger.exception("follower: rematch replay failed")
        elif tag == "shutdown":
            logger.info("follower: shutdown op received; exiting")
            return False
        else:
            raise RuntimeError(f"unknown dispatch op {tag!r}")
        return True


def _leader_alive(host: str, port: int, timeout: float = 5.0) -> bool:
    """Split-brain guard: before self-promoting on stream loss, probe
    whether the leader's dispatch server still accepts connections.  A
    follower the LEADER evicted (transient send error, digest timeout)
    sees the same EOF a leader death produces — promoting then would
    stand up a second live frontend.  A leader that answers the probe is
    alive: the follower must exit, not promote.  (Conservative by
    design: a wedged-but-listening leader suppresses promotion.)"""
    try:
        probe = socket.create_connection((host, int(port)),
                                         timeout=timeout)
        probe.close()
        return True
    except OSError:
        return False


def promote_follower(session: _FollowerSession):
    """Promote this follower's replicas into a serving leader (ISSUE 8).

    The replica corpus (bootstrap snapshot + replayed commits) and the
    replicated link DB (bootstrap link state + the published op stream up
    to the applied watermark) ARE the promoted leader's state — exactly
    the join-bootstrap path run in reverse.  This builds full serving
    workloads around them (real processors with host finalization, match
    listeners writing into the replica link DBs) and returns a ``DukeApp``
    the caller binds an HTTP server to (``service.app.serve``).

    The session's fencing epoch is bumped BEFORE hand-over: any frame
    still in flight from the deposed leader carries the old epoch and is
    rejected (``stale_rejected``), so a zombie ex-leader that comes back
    mid-promotion cannot corrupt the promoted group's state.
    """
    from ..engine.workload import adopt_workload
    from ..links.replica import ReplicaLinkDatabase
    from ..service.app import DukeApp

    if not session.replicas:
        raise RuntimeError("nothing to promote: no bootstrapped replicas")
    backend, config_string = session._incoming
    sc = session._parse_config(config_string)
    session.adopt_epoch(session.epoch + 1)
    dedups: Dict[str, object] = {}
    linkages: Dict[str, object] = {}
    for (kind, name), replica in session.replicas.items():
        wc = (sc.deduplications if kind == "deduplication"
              else sc.record_linkages)[name]
        link_db = session.link_replicas.get((kind, name))
        if link_db is None:
            link_db = ReplicaLinkDatabase()
        wl = adopt_workload(
            wc, sc, backend=backend, index=replica.index,
            link_database=link_db,
            # the follower-local bootstrap store keeps backing the lazy
            # record mirror, and the promoted write path persists new
            # batches into it store-first — the frontend's own order
            record_store=replica._asm.store,
        )
        (dedups if kind == "deduplication" else linkages)[name] = wl
    session.promoted = True  # the app owns the indexes/link DBs now
    telemetry.DISPATCH_EPOCH.set(session.epoch)  # dukecheck: ignore[DK502] once: promotion
    logger.warning(
        "follower %d PROMOTED to leader at epoch %d (%d workload(s), "
        "link watermark(s) %s)",
        session.follower_idx, session.epoch, len(session.replicas),
        {k[1]: getattr(db, "applied_seq", 0)
         for k, db in session.link_replicas.items()},
    )
    return DukeApp(sc, backend=backend, persistent=False,
                   prebuilt=(dedups, linkages))


def follower_main(poll_timeout_ms: int = None) -> None:
    """Follower process entrypoint: connect to the frontend's dispatch
    stream and replay mesh ops until shutdown/EOF.  Call after
    ``multihost.initialize()`` in a process with ``jax.process_index() >
    0``; never returns until the job ends.

    HA extensions (ISSUE 8), both off unless configured:

      * ``DUKE_REPLICA_HTTP_PORT`` — serve the replica read plane
        (``?since=`` feeds, /stats, /metrics, /healthz with replication
        lag) from this follower while it replays;
      * ``DUKE_PROMOTE_PORT`` — on leader loss (stream EOF/reset after a
        completed bootstrap, without a clean shutdown op), promote this
        follower's replicas to a serving leader and bind the full HTTP
        frontend on that port instead of exiting.
    """
    from ..utils.jit_cache import enable_persistent_cache

    enable_persistent_cache()
    addr = env_str("DUKE_DISPATCH_ADDR")
    via_addr_env = addr is not None
    if addr is None:
        timeout = poll_timeout_ms or int(_CONNECT_TIMEOUT_S * 1000)
        addr = _kv_client().blocking_key_value_get(_KV_ADDR_KEY, timeout)
    addr, _, token = addr.partition("/")
    # a pre-shared secret wins over the KV-published token; it is also the
    # ONLY way the DUKE_DISPATCH_ADDR bypass can authenticate (a follower
    # configured by address alone never sees the frontend's random token)
    token = _join_token() or token
    if not token:
        raise RuntimeError(
            "no join token is available — set DUKE_DISPATCH_TOKEN on this "
            "follower"
            + (" (required with DUKE_DISPATCH_ADDR)" if via_addr_env else
               " (the frontend published a bare address, meaning it runs "
               "with DUKE_DISPATCH_TOKEN set)")
        )
    host, _, port = addr.rpartition(":")
    logger.info("follower: connecting to dispatch stream at %s", addr)
    sock = socket.create_connection((host, int(port)),
                                    timeout=_CONNECT_TIMEOUT_S)
    import jax

    follower_idx = jax.process_index() - 1
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # raw-bytes join (Dispatcher.start); carries this follower's stable
    # index so leader-side identity matches DUKE_FAULTS coordinates
    sock.sendall(_hello_frame(token, follower_idx))
    sock.settimeout(None)  # ops arrive whenever the frontend has work

    session = _FollowerSession(sock.sendall, follower_idx=follower_idx)
    plane = None
    replica_port = env_int("DUKE_REPLICA_HTTP_PORT", 0)
    any_op = False
    clean_shutdown = False
    try:
        while True:
            try:
                op, epoch, seq = _recv_op(sock)
            except (EOFError, OSError):
                if not any_op:
                    # EOF before the first op means the frontend dropped
                    # us at the handshake — almost always a join-token
                    # mismatch (one-sided DUKE_DISPATCH_TOKEN).  Exiting
                    # cleanly here would hide the misconfiguration from
                    # orchestrators while the frontend blocks out its
                    # whole accept timeout.
                    raise RuntimeError(
                        "dispatch stream closed before any op arrived — "
                        "the frontend likely rejected this follower's "
                        "join token (is DUKE_DISPATCH_TOKEN set "
                        "identically on both sides?)"
                    )
                logger.info("follower: dispatch stream closed")
                break
            any_op = True
            if plane is None and replica_port and session.replicas:
                from ..service.replica_plane import serve_replica_plane

                plane = serve_replica_plane(session, port=replica_port)
            if not session.handle_frame(op, epoch, seq):
                clean_shutdown = True
                break
        if not clean_shutdown and session.replicas:
            promote_port = env_int("DUKE_PROMOTE_PORT", 0)
            if promote_port and _leader_alive(host, int(port)):
                # the stream died but the leader still answers: WE were
                # evicted, the leader was not lost.  Promoting here would
                # split-brain the group (two live frontends) — exit and
                # let the orchestrator restart this follower into a
                # fresh join instead.
                raise RuntimeError(
                    "dispatch stream lost but the leader still accepts "
                    "connections — this follower was evicted; refusing "
                    "to promote (split-brain guard). Restart to rejoin."
                )
            if promote_port:
                # leader loss without a shutdown op: promote and re-bind
                # the HTTP frontend (the replica plane, if any, yields to
                # the full surface)
                if plane is not None:
                    plane.shutdown()
                    plane = None
                from ..service.app import serve

                app = promote_follower(session)
                server = serve(app, port=promote_port)
                logger.warning(
                    "promoted frontend serving on port %d", promote_port
                )
                server.serve_forever()
    finally:
        if plane is not None:
            plane.shutdown()
        session.close()
        sock.close()


# -- frontend entry ----------------------------------------------------------


def start_dispatcher(app) -> Dispatcher:
    """Create+start the frontend dispatcher for a multi-process job."""
    if app.backend not in ("sharded", "sharded-brute"):
        raise RuntimeError(
            "multi-host serving requires --backend sharded or sharded-brute "
            f"(got {app.backend!r}); single-device backends cannot span hosts"
        )
    d = Dispatcher(app)
    d.start()
    return d
