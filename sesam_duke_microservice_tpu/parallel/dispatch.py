"""Multi-host serving: single-controller dispatch of mesh operations.

The reference wires its matcher directly into the HTTP handlers of one JVM
(App.java:343-345,1005); SURVEY.md section 5.8 defines the TPU-native
scale-out as a single-controller dispatch model over the JAX collective
stack.  This module is that model's control plane:

  * **Frontend** (process 0): serves the full REST surface and owns every
    host-side subsystem — ingest, link databases, listeners, durable
    stores, feeds.  Each mesh-touching operation (a corpus commit, a
    scoring pass) is broadcast to the followers *before* the frontend
    executes it.
  * **Followers** (process 1..N-1): no HTTP, no link state — each runs a
    replica of every workload's sharded index (corpus host mirror + the
    jitted shard_map programs) and replays the frontend's operation
    stream in order, entering the same device programs in lockstep so the
    ``all_gather``/``psum`` collectives rendezvous across hosts
    (ICI within a slice, DCN across — parallel/multihost.py).

Correctness rests on two invariants:

  1. **Bit-identical host mirrors.**  In the multi-controller model each
     process supplies its local shards of every global array from its own
     host corpus mirror, so the mirrors must match across processes
     exactly.  Followers bootstrap from the frontend's corpus state (the
     snapshot wire format of ``DeviceIndex.snapshot_save`` plus the
     record mirror) and then apply the same deterministic mutations in
     the same order (op ``commit``).
  2. **Identical device-program order.**  XLA executes each process's
     programs in dispatch order; collectives deadlock if two processes
     enqueue the same programs in different orders.  The frontend holds
     ``Dispatcher.op_lock`` across every broadcast+execute section
     (serializing across workloads), and followers replay the single op
     stream sequentially.  Escalation re-runs (``resolve_block``) are
     driven by replicated device outputs, so every process makes the same
     widening decision at the same point — including the double-buffered
     dispatch order of ``DeviceProcessor`` (the follower runs the same
     loop structure via ``_score_blocks``).

The op channel is a plain length-prefixed-pickle TCP stream from the
frontend to each follower, opened only after a fixed-format raw-bytes
join handshake (no pickle ever touches unauthenticated bytes); the
frontend's address is published through the jax.distributed coordination
KV store (rendezvous only — the data path never rides the coordinator).  A dead follower surfaces as a hung
collective, the standard JAX multi-controller failure mode; the service
logs the follower set at startup so operators can correlate.

Every REST operation is supported multi-host, including the ring
re-match (r4): its query-sharded outputs materialize through
``process_allgather`` — a collective the follower replay enters in
lockstep (engine/rematch.py).
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import struct
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("dispatch")

# rendezvous key in the jax.distributed coordination service KV store
_KV_ADDR_KEY = "sesam_duke/dispatch/addr"
_CONNECT_TIMEOUT_S = float(os.environ.get("DUKE_DISPATCH_TIMEOUT", "600"))

_DISPATCHER: Optional["Dispatcher"] = None


def current() -> Optional["Dispatcher"]:
    """The active frontend dispatcher, or None (single-process serving and
    follower processes both see None — the broadcast hooks no-op)."""
    return _DISPATCHER


import contextlib


@contextlib.contextmanager
def latch_on_failure(d: Optional["Dispatcher"], reason_prefix: str):
    """THE post-broadcast execution guard: once an op has been broadcast,
    a frontend that fails to execute it locally leaves followers ahead on
    the op stream (mirror divergence, or un-matched collective programs)
    — so any exception latches the dispatcher before propagating, and
    every further mesh op refuses loudly instead of hanging a desynced
    collective.  ``d=None`` (single-process) passes exceptions through
    untouched.  One helper, used by every broadcast site (commit / score
    / rematch), so the invariant cannot drift between them."""
    if d is None:
        yield
        return
    try:
        yield
    except BaseException as e:
        d.mark_failed(f"{reason_prefix}: {e!r}")
        raise


# -- wire format -------------------------------------------------------------

# Join handshake: a FIXED-FORMAT raw-bytes frame — magic + sha256 hexdigest
# of the join token — sent by the follower before anything else.  The
# frontend authenticates this frame with hmac.compare_digest BEFORE any
# pickle ever touches bytes from the socket: unpickling attacker bytes is
# arbitrary code execution, so the pickle op stream begins strictly after
# authentication (advisor r4).  Hashing the token keeps the frame
# fixed-length for any operator-chosen DUKE_DISPATCH_TOKEN.
_HELLO_MAGIC = b"SDMT1"
_HELLO_LEN = len(_HELLO_MAGIC) + 64  # magic + sha256 hexdigest (ascii)


def _hello_frame(token: str) -> bytes:
    import hashlib

    return _HELLO_MAGIC + hashlib.sha256(token.encode()).hexdigest().encode()


def _join_token() -> Optional[str]:
    """Operator-provided pre-shared secret, if any.  Set on BOTH sides it
    replaces the per-run random token, which is what makes the
    DUKE_DISPATCH_ADDR rendezvous bypass actually usable (a follower
    outside the coordination service can never learn a random token)."""
    return os.environ.get("DUKE_DISPATCH_TOKEN") or None


def _send_msg(sock: socket.socket, obj) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack(">Q", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("dispatch channel closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    (n,) = struct.unpack(">Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


def _kv_client():
    """The jax.distributed coordination-service KV client (private API —
    isolated here so an upstream rename breaks exactly one function; the
    DUKE_DISPATCH_ADDR env var bypasses it entirely)."""
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "jax.distributed is not initialized (multi-host dispatch needs "
            "the coordination service, or set DUKE_DISPATCH_ADDR)"
        )
    return client


def _env_fingerprint() -> dict:
    """Shape-relevant configuration that must match across processes (a
    mismatch would compile different programs → collective deadlock)."""
    import jax

    from ..engine import device_matcher as DM

    return {
        "jax": jax.__version__,
        "devices": jax.device_count(),
        "chunk": DM._CHUNK,
        "buckets": DM._QUERY_BUCKETS,
        "update_slice": DM._UPDATE_SLICE,
        "value_slots_max": DM._VALUE_SLOTS_MAX,
        "initial_top_k": DM._INITIAL_TOP_K,
        "ann_dim": os.environ.get("DEVICE_ANN_DIM", "256"),
        "ann_c": os.environ.get("DEVICE_ANN_CANDIDATES", "64"),
        # every env knob that sizes a feature tensor (ops.features): a
        # mismatch here compiles different-shape programs per process and
        # deadlocks the first cross-host collective
        "max_chars": os.environ.get("DEVICE_MAX_CHARS", ""),
        "max_chars_cap": os.environ.get("DEVICE_MAX_CHARS_CAP", ""),
        "demote_chars": os.environ.get("DEVICE_DEMOTE_CHARS", ""),
        "max_grams": os.environ.get("DEVICE_MAX_GRAMS", ""),
        "max_tokens": os.environ.get("DEVICE_MAX_TOKENS", ""),
        "value_slots": os.environ.get("DEVICE_VALUE_SLOTS", ""),
    }


# -- frontend ----------------------------------------------------------------


class Dispatcher:
    """Frontend-side op broadcaster (process 0 of a multi-host job)."""

    def __init__(self, app):
        self.app = app
        # serializes every broadcast+execute section across workloads so
        # all processes enqueue device programs in one global order
        self.op_lock = threading.RLock()
        self._send_lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._server: Optional[socket.socket] = None
        self._closed = False
        # latched on the first broadcast failure: once any follower
        # missed an op, its mirror is behind forever (ops are not
        # replayable), so every further mesh op must refuse loudly —
        # serving partial-mesh results or deadlocking a collective would
        # both be silent corruption.  Recovery = restart the job.
        self._failed: Optional[str] = None

    # - lifecycle -

    def start(self) -> None:
        import secrets

        import jax

        n_followers = jax.process_count() - 1
        if n_followers <= 0:
            raise RuntimeError("Dispatcher.start() needs a multi-process job")
        bind_host = os.environ.get("DUKE_DISPATCH_BIND", "0.0.0.0")
        advertise = os.environ.get("DUKE_DISPATCH_HOST")
        port = int(os.environ.get("DUKE_DISPATCH_PORT", "0"))
        self._server = socket.create_server((bind_host, port))
        actual_port = self._server.getsockname()[1]
        if advertise is None:
            advertise = socket.gethostname()
        # join token: a pre-shared DUKE_DISPATCH_TOKEN when the operator
        # set one, else per-run random, published only through the
        # coordination-service KV store — so a follower slot requires the
        # secret or coordination-service access; an arbitrary process that
        # can reach the TCP port cannot claim a slot (and receive the
        # bootstrap's record payload) or starve the real followers out of
        # theirs.  The handshake is raw bytes (_hello_frame): nothing from
        # an unauthenticated socket is ever unpickled.
        psk = _join_token()
        token = psk or secrets.token_hex(16)
        addr = f"{advertise}:{actual_port}"
        # a pre-shared secret is long-lived (reused across runs), so it
        # must never widen into the KV store's trust boundary — publish
        # the address alone and let followers supply the secret from
        # their own env (a per-run random token, by contrast, is exactly
        # the thing the KV rendezvous exists to distribute)
        _kv_client().key_value_set(
            _KV_ADDR_KEY, addr if psk else f"{addr}/{token}"
        )
        logger.info(
            "dispatch: waiting for %d follower(s) on %s", n_followers, addr
        )
        self._accept_followers(n_followers, token)
        self._tag_workloads(self.app.deduplications, self.app.record_linkages)
        self._bootstrap_followers()
        global _DISPATCHER
        _DISPATCHER = self

    def _accept_followers(self, n_followers: int, token: str) -> None:
        """Accept exactly ``n_followers`` authenticated connections.

        Authentication reads a FIXED-LENGTH raw frame and compares it in
        constant time — pickle.loads never sees bytes from a socket that
        has not presented the join token (unpickling attacker-controlled
        bytes is arbitrary code execution, advisor r4 high)."""
        import hmac

        expected_hello = _hello_frame(token)
        self._server.settimeout(_CONNECT_TIMEOUT_S)
        while len(self._conns) < n_followers:
            conn, peer = self._server.accept()
            try:
                conn.settimeout(30.0)
                hello = _recv_exact(conn, _HELLO_LEN)
                if not hmac.compare_digest(hello, expected_hello):
                    raise ValueError("bad join token")
                conn.settimeout(None)
            except Exception as e:
                logger.warning(
                    "dispatch: rejected connection from %s (%s)", peer, e
                )
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.append(conn)
            logger.info("dispatch: follower connected from %s", peer)

    def _bootstrap_followers(self) -> None:
        self.broadcast((
            "bootstrap",
            self.app.backend,
            self.app.config_string,
            self._capture_states(),
            _env_fingerprint(),
        ))

    def close(self) -> None:
        global _DISPATCHER
        if self._closed:
            return
        self._closed = True
        try:
            self.broadcast(("shutdown",))
        except Exception:
            pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._server is not None:
            self._server.close()
        if _DISPATCHER is self:
            _DISPATCHER = None

    # - ops -

    def broadcast(self, op: tuple) -> None:
        """Send one op to every follower (in one global order).

        A send failure latches the dispatcher: the dead follower's mirror
        is now permanently behind, so every subsequent op raises instead
        of diverging the mesh (the standard JAX multi-controller stance —
        a lost process ends the job)."""
        if self._failed is not None:
            raise RuntimeError(
                "multi-host dispatch is down (a follower lost an op: "
                f"{self._failed}); restart the job to recover"
            )
        data = pickle.dumps(op, protocol=pickle.HIGHEST_PROTOCOL)
        frame = struct.pack(">Q", len(data)) + data
        with self._send_lock:
            for conn in self._conns:
                try:
                    conn.sendall(frame)
                except OSError as e:
                    self._failed = repr(e)
                    logger.error(
                        "dispatch: broadcast to a follower failed (%s); "
                        "halting mesh ops — restart the job", e,
                    )
                    raise RuntimeError(
                        f"multi-host dispatch broadcast failed: {e}"
                    ) from e

    def mark_failed(self, reason: str) -> None:
        """Latch the dispatcher down after an op-stream desync the sender
        detected OUTSIDE broadcast() (e.g. the frontend aborted mid-run
        after telling followers to run a full pass): every further mesh
        op raises instead of hanging on a desynced collective."""
        if self._failed is None:
            self._failed = reason
            logger.error(
                "dispatch: halting mesh ops (%s) — restart the job", reason
            )

    def on_reload(self, sc, new_dedups: Dict, new_linkages: Dict) -> None:
        """Called by DukeApp.apply_config after building the replacement
        workloads (old locks held, nothing in flight): re-tags the new
        indexes and ships followers the new config + corpus states."""
        self._tag_workloads(new_dedups, new_linkages)
        states = self._capture_states(new_dedups, new_linkages)
        self.broadcast(("reload", self.app.backend, sc.config_string, states))

    # - helpers -

    def _tag_workloads(self, dedups: Dict, linkages: Dict) -> None:
        for kind, registry in (("deduplication", dedups),
                               ("recordlinkage", linkages)):
            for name, wl in registry.items():
                wl.index._dispatch_key = (kind, name)

    def _capture_states(self, dedups=None, linkages=None) -> Dict:
        dedups = self.app.deduplications if dedups is None else dedups
        linkages = self.app.record_linkages if linkages is None else linkages
        states = {}
        for kind, registry in (("deduplication", dedups),
                               ("recordlinkage", linkages)):
            for name, wl in registry.items():
                states[(kind, name)] = _capture_state(wl.index)
        return states


def _capture_state(index) -> dict:
    """Corpus bootstrap payload for one workload: the snapshot wire format
    (feature tensors, masks, row ids, value-slot widths — row layout
    preserved exactly, which invariant 1 requires) plus the record mirror
    the follower needs for value-slot rebuilds and snapshot adoption."""
    snapshot = None
    if getattr(index, "corpus", None) is not None and index.corpus.size > 0:
        fd, tmp = tempfile.mkstemp(suffix=".npz")
        os.close(fd)
        try:
            index.snapshot_save(tmp)
            with open(tmp, "rb") as f:
                snapshot = f.read()
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return {
        "snapshot": snapshot,
        "records": list(index.records.values()),
    }


# -- follower ----------------------------------------------------------------


class FollowerProcessor:
    """Device-program replayer for one workload replica: the scoring side
    of ``DeviceProcessor`` with host finalization off.  It deliberately
    reuses ``DeviceProcessor._score_blocks`` so the dispatch order
    (double-buffered blocks, escalation re-runs) is the frontend's
    bit-for-bit — drift there deadlocks collectives (invariant 2)."""

    def __init__(self, schema, index, *, group_filtering: bool):
        from ..engine.device_matcher import DeviceProcessor

        self._proc = DeviceProcessor(
            schema, index, group_filtering=group_filtering
        )
        self._proc.finalize_survivors = False

    def score(self, records) -> None:
        self._proc._score_blocks(records)


class _Replica:
    """One workload's follower-side state: sharded index + processor."""

    def __init__(self, sc, kind: str, name: str, backend: str, state: dict):
        registry = (sc.deduplications if kind == "deduplication"
                    else sc.record_linkages)
        wc = registry[name]
        if backend == "sharded-brute":
            from ..engine.sharded_matcher import ShardedDeviceIndex

            self.index = ShardedDeviceIndex(wc.duke, tunables=sc.tunables)
        else:
            from ..engine.sharded_matcher import ShardedAnnIndex

            self.index = ShardedAnnIndex(wc.duke, tunables=sc.tunables)
        self.processor = FollowerProcessor(
            wc.duke, self.index, group_filtering=wc.is_record_linkage
        )
        if state["snapshot"]:
            self._adopt(state)

    def _adopt(self, state: dict) -> None:
        import numpy as np

        fd, tmp = tempfile.mkstemp(suffix=".npz")
        os.close(fd)
        try:
            with open(tmp, "wb") as f:
                f.write(state["snapshot"])
            # trusted bootstrap from the live frontend: the content compare
            # is satisfied by the snapshot's own stamp (the staleness guard
            # protects restarts from DISK state; this state was captured
            # from a quiesced live corpus seconds ago)
            with np.load(tmp) as data:
                content = str(data["__content"])
            records_by_id = {r.record_id: r for r in state["records"]}
            if not self.index.snapshot_load(
                tmp, records_by_id, content_hash=content
            ):
                raise RuntimeError(
                    "follower bootstrap: corpus state rejected (plan/env "
                    "mismatch with the frontend?)"
                )
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def close(self) -> None:
        self.index.close()


def follower_main(poll_timeout_ms: int = None) -> None:
    """Follower process entrypoint: connect to the frontend's dispatch
    stream and replay mesh ops until shutdown/EOF.  Call after
    ``multihost.initialize()`` in a process with ``jax.process_index() >
    0``; never returns until the job ends."""
    from ..core.config import parse_config
    from ..utils.jit_cache import enable_persistent_cache

    enable_persistent_cache()
    addr = os.environ.get("DUKE_DISPATCH_ADDR")
    via_addr_env = addr is not None
    if addr is None:
        timeout = poll_timeout_ms or int(_CONNECT_TIMEOUT_S * 1000)
        addr = _kv_client().blocking_key_value_get(_KV_ADDR_KEY, timeout)
    addr, _, token = addr.partition("/")
    # a pre-shared secret wins over the KV-published token; it is also the
    # ONLY way the DUKE_DISPATCH_ADDR bypass can authenticate (a follower
    # configured by address alone never sees the frontend's random token)
    token = _join_token() or token
    if not token:
        raise RuntimeError(
            "no join token is available — set DUKE_DISPATCH_TOKEN on this "
            "follower"
            + (" (required with DUKE_DISPATCH_ADDR)" if via_addr_env else
               " (the frontend published a bare address, meaning it runs "
               "with DUKE_DISPATCH_TOKEN set)")
        )
    host, _, port = addr.rpartition(":")
    logger.info("follower: connecting to dispatch stream at %s", addr)
    sock = socket.create_connection((host, int(port)),
                                    timeout=_CONNECT_TIMEOUT_S)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.sendall(_hello_frame(token))  # raw-bytes join (Dispatcher.start)
    sock.settimeout(None)  # ops arrive whenever the frontend has work

    replicas: Dict[Tuple[str, str], _Replica] = {}

    def rebuild(backend: str, config_string: str, states: dict) -> None:
        for replica in replicas.values():
            replica.close()
        replicas.clear()
        sc = parse_config(config_string)
        for (kind, name), state in states.items():
            replicas[(kind, name)] = _Replica(sc, kind, name, backend, state)
        logger.info(
            "follower: %d workload replica(s) ready (%s)",
            len(replicas), backend,
        )

    try:
        while True:
            try:
                op = _recv_msg(sock)
            except EOFError:
                logger.info("follower: dispatch stream closed; exiting")
                return
            tag = op[0]
            if tag == "bootstrap":
                _, backend, config_string, states, fingerprint = op
                mine = _env_fingerprint()
                if fingerprint != mine:
                    raise RuntimeError(
                        "follower env/shape fingerprint mismatch vs "
                        f"frontend: {fingerprint} != {mine} — all processes "
                        "must run identical DEVICE_*/schema configuration"
                    )
                rebuild(backend, config_string, states)
            elif tag == "reload":
                _, backend, config_string, states = op
                rebuild(backend, config_string, states)
            elif tag == "commit":
                _, key, records = op
                replica = replicas[key]
                try:
                    for record in records:
                        replica.index.index(record)
                    replica.index.commit()
                except Exception:
                    # deterministic engine errors raise SYMMETRICALLY on
                    # the frontend (same code, same inputs), so surviving
                    # them keeps the mirrors consistent; dying here would
                    # let one bad request wedge the whole mesh.  An
                    # asymmetric (hardware) failure resurfaces on the next
                    # op and the job restarts per the module's stance.
                    logger.exception("follower: commit replay failed")
            elif tag == "score":
                _, key, records = op
                try:
                    replicas[key].processor.score(records)
                except Exception:
                    logger.exception("follower: score replay failed")
            elif tag == "rematch":
                _, key, block_rows = op
                from ..engine.rematch import replay_rematch

                replica = replicas[key]
                try:
                    replay_rematch(replica.index, replica.processor._proc,
                                   query_block_rows=block_rows)
                except Exception:
                    logger.exception("follower: rematch replay failed")
            elif tag == "shutdown":
                logger.info("follower: shutdown op received; exiting")
                return
            else:
                raise RuntimeError(f"unknown dispatch op {tag!r}")
    finally:
        for replica in replicas.values():
            try:
                replica.close()
            except Exception:
                pass
        sock.close()


# -- frontend entry ----------------------------------------------------------


def start_dispatcher(app) -> Dispatcher:
    """Create+start the frontend dispatcher for a multi-process job."""
    if app.backend not in ("sharded", "sharded-brute"):
        raise RuntimeError(
            "multi-host serving requires --backend sharded or sharded-brute "
            f"(got {app.backend!r}); single-device backends cannot span hosts"
        )
    d = Dispatcher(app)
    d.start()
    return d
